"""Namespaces and resource quotas (reference: nomad/structs/structs.go
Namespace:5353, nomad/structs/quota.ent.go QuotaSpec/QuotaLimit/QuotaUsage).

Namespaces partition the job space; a namespace may reference a
``QuotaSpec`` by name, and every namespace referencing a spec gets its
own budget of that spec's limits (per-namespace budget semantics — the
spec is a template, not an aggregate pool).  Quota usage accounting is
replicated state maintained inside the FSM apply cone (see
``state/store.py``) so enforcement is deterministic across survivors.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class Namespace:
    """A first-class replicated namespace (CRUD through FSM entries)."""
    name: str = "default"
    description: str = ""
    # name of the QuotaSpec governing this namespace ("" = unlimited)
    quota: str = ""
    create_index: int = 0
    modify_index: int = 0


@dataclass
class QuotaSpec:
    """Resource ceiling template.  ``None`` limits are unlimited; the
    check is dimension-wise (cpu shares, memory MB, device count,
    alloc count) against the namespace's live usage."""
    name: str = ""
    description: str = ""
    cpu: Optional[int] = None           # MHz shares
    memory_mb: Optional[int] = None
    devices: Optional[int] = None       # accelerator device count
    allocs: Optional[int] = None        # live (non-terminal) alloc count
    create_index: int = 0
    modify_index: int = 0

    def admits(self, usage: Dict[str, int]) -> bool:
        """True when `usage` (a would-be post-placement total) fits."""
        for dim, limit in (("cpu", self.cpu), ("memory_mb", self.memory_mb),
                           ("devices", self.devices), ("allocs", self.allocs)):
            if limit is not None and usage.get(dim, 0) > limit:
                return False
        return True

    def exceeded_dims(self, usage: Dict[str, int]) -> list:
        out = []
        for dim, limit in (("cpu", self.cpu), ("memory_mb", self.memory_mb),
                           ("devices", self.devices), ("allocs", self.allocs)):
            if limit is not None and usage.get(dim, 0) > limit:
                out.append(dim)
        return out


def alloc_quota_usage(alloc) -> Dict[str, int]:
    """The quota-relevant resource vector of one allocation.

    Derived purely from the alloc's own fields (no clock, no store reads
    beyond the alloc) so the FSM-side usage accounting stays replica
    deterministic."""
    cmp = alloc.comparable_resources()
    devices = 0
    ar = alloc.allocated_resources
    for tres in (ar.tasks.values() if ar is not None else ()):
        for dev in tres.devices:
            devices += len(dev.get("device_ids", []) or [])
    return {"cpu": int(cmp.cpu_shares), "memory_mb": int(cmp.memory_mb),
            "devices": devices, "allocs": 1}


def usage_add(usage: Dict[str, int], delta: Dict[str, int],
              sign: int = 1) -> None:
    for k, v in delta.items():
        usage[k] = usage.get(k, 0) + sign * v
