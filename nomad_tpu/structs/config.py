"""Cluster-wide scheduler configuration (reference: nomad/structs/operator.go:144-169
SchedulerConfiguration), settable live via the operator API and read at
stack-build time.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict


SCHEDULER_ALGORITHM_BINPACK = "binpack"
SCHEDULER_ALGORITHM_SPREAD = "spread"


@dataclass
class PreemptionConfig:
    system_scheduler_enabled: bool = True
    sysbatch_scheduler_enabled: bool = False
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    scheduler_algorithm: str = SCHEDULER_ALGORITHM_BINPACK
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    memory_oversubscription_enabled: bool = False
    reject_job_registration: bool = False
    pause_eval_broker: bool = False
    # weighted fair-share dequeue in the eval broker: per-namespace
    # stride scheduling over `namespace_weights` (unlisted namespaces
    # get `default_namespace_weight`).  With a single namespace (or
    # uniform weights) the dequeue order is indistinguishable from the
    # global (-priority, seq) order, so enabled-by-default is safe.
    fair_dequeue_enabled: bool = True
    default_namespace_weight: int = 1
    namespace_weights: Dict[str, int] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def effective_scheduler_algorithm(self) -> str:
        return self.scheduler_algorithm or SCHEDULER_ALGORITHM_BINPACK

    def preemption_enabled(self, scheduler_type: str) -> bool:
        p = self.preemption_config
        return {
            "system": p.system_scheduler_enabled,
            "sysbatch": p.sysbatch_scheduler_enabled,
            "batch": p.batch_scheduler_enabled,
            "service": p.service_scheduler_enabled,
        }.get(scheduler_type, False)
