"""Evaluation model (reference: nomad/structs/structs.go Evaluation:10737)."""
from __future__ import annotations

import uuid

from nomad_tpu.utils import generate_uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class EvalStatus:
    BLOCKED = "blocked"
    PENDING = "pending"
    COMPLETE = "complete"
    FAILED = "failed"
    CANCELLED = "canceled"

    @staticmethod
    def terminal(status: str) -> bool:
        return status in (EvalStatus.COMPLETE, EvalStatus.FAILED, EvalStatus.CANCELLED)


class EvalTrigger:
    JOB_REGISTER = "job-register"
    JOB_DEREGISTER = "job-deregister"
    PERIODIC_JOB = "periodic-job"
    NODE_DRAIN = "node-drain"
    NODE_UPDATE = "node-update"
    ALLOC_STOP = "alloc-stop"
    SCHEDULED = "scheduled"
    ROLLING_UPDATE = "rolling-update"
    DEPLOYMENT_WATCHER = "deployment-watcher"
    FAILED_FOLLOW_UP = "failed-follow-up"
    MAX_DISCONNECT_TIMEOUT = "max-disconnect-timeout"
    RECONNECT = "reconnect"
    MAX_PLANS = "max-plan-attempts"
    RETRY_FAILED_ALLOC = "alloc-failure"
    QUEUED_ALLOCS = "queued-allocs"
    PREEMPTION = "preemption"
    JOB_SCALING = "job-scaling"


@dataclass
class Evaluation:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"             # scheduler type
    triggered_by: str = EvalTrigger.JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EvalStatus.PENDING
    status_description: str = ""
    wait_until: float = 0.0           # absolute time for delayed evals
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    related_evals: List[str] = field(default_factory=list)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)  # tg -> queued count
    leader_ack: str = ""              # broker token, not persisted
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    def terminal(self) -> bool:
        return EvalStatus.terminal(self.status)

    def should_enqueue(self) -> bool:
        return self.status == EvalStatus.PENDING

    def should_block(self) -> bool:
        return self.status == EvalStatus.BLOCKED

    def make_plan(self, job) -> "Plan":
        from nomad_tpu.structs.plan import Plan
        return Plan(
            eval_id=self.id,
            priority=self.priority if job is None else job.priority,
            job=job,
            all_at_once=False if job is None else job.all_at_once,
        )

    def copy(self) -> "Evaluation":
        import copy as _copy
        return _copy.deepcopy(self)


def new_eval(**kw) -> Evaluation:
    return Evaluation(**kw)
