"""Single declared registry for every ``NOMAD_TPU_*`` tuning knob.

Every environment variable the runtime consults is declared here —
name, default, type, one-line doc — and read through the typed
accessors (`get_str` / `get_int` / `get_float` / `get_bool`).  The
`knob-registry` static checker (`nomad_tpu/analysis/knob_registry.py`)
enforces the contract from the other side: a raw ``os.environ`` /
``getenv`` read of a ``NOMAD_TPU_*`` literal anywhere outside this file
is a finding, as is a registered knob nothing reads (dead entry) or one
missing from the README knob table (doc drift).

Accessors hit ``os.environ`` at *call* time — nothing is cached — so
tests can monkeypatch the environment and `override()` can scope a
value to a block.  An empty string counts as unset (several knobs use
"" for "auto"); the ``default=`` parameter lets a call site supply a
dynamic fallback (e.g. ``NOMAD_TPU_WAVE`` defaulting to the scheduler
count) that overrides the registry default.

Regenerate the README table with ``python -m nomad_tpu.knobs``.
"""
from __future__ import annotations

import contextlib
import os
from typing import Dict, Iterator, Mapping, Optional

# Marker the knob-registry checker keys on to find this file in a
# corpus (fixture corpora declare their own registry module the same
# way).
_KNOB_REGISTRY = True


class Knob:
    """One registered knob: wire default (string form, "" = unset/auto),
    type name ("str" | "int" | "float" | "bool"), one-line doc."""

    __slots__ = ("default", "type", "doc")

    def __init__(self, default: str, type: str, doc: str) -> None:
        self.default = default
        self.type = type
        self.doc = doc


# The registry is a plain dict literal of Knob(...) calls with constant
# arguments so the static checker can read it without importing us.
KNOBS: Dict[str, Knob] = {
    # -- parallel engine / serving mesh ------------------------------
    "NOMAD_TPU_ENGINE": Knob(
        "1", "bool",
        "`0` bypasses the batching engine (direct kernel calls)"),
    "NOMAD_TPU_SHARD": Knob(
        "1", "bool",
        "`0` disables the multi-device serving mesh entirely"),
    "NOMAD_TPU_SHARD_MIN": Knob(
        "128", "int",
        "minimum padded node rows before dispatches route over the "
        "`('node_shard','wave')` mesh (`shard_min_nodes`)"),
    "NOMAD_TPU_WAVE_SHARDS": Knob(
        "", "int",
        "wave extent of the 2-D serving mesh (`wave_mesh_shape`); "
        "empty = auto, a non-divisor of the device count falls back "
        "to 1"),
    "NOMAD_TPU_FUSE": Knob(
        "1", "bool",
        "`0` splits bulk waves into per-group device dispatches "
        "instead of one fused part per wave"),
    "NOMAD_TPU_DONATE": Knob(
        "1", "bool",
        "`0` disables donated usage-basis carries (kernel falls back "
        "to functional updates + host re-upload)"),
    "NOMAD_TPU_OVERLAP": Knob(
        "1", "bool",
        "`0` disables upload/compute overlap (each bulk dispatch "
        "drains before the next uploads; requires donation)"),
    "NOMAD_TPU_BULK_BYTES": Knob(
        "268435456", "int",
        "byte budget for one bulk dispatch's stacked per-eval "
        "tensors; caps the eval-axis chain length at large N"),
    "NOMAD_TPU_WARM_THREADS": Knob(
        "4", "int",
        "parallelism of `engine.warmup` kernel-variant compilation"),
    "NOMAD_TPU_PLAN_BATCH": Knob(
        "64", "int",
        "plan applier batch size (commit coalescing; sized to swallow "
        "a full feeder wave per raft apply)"),
    "NOMAD_TPU_PIPELINE_DEPTH": Knob(
        "2", "int",
        "in-flight commit waves a worker may run ahead of "
        "(double-buffer depth); `0` restores blocking submit"),
    "NOMAD_TPU_WAVE": Knob(
        "", "int",
        "max evals the `EvalWaveFeeder` drains per broker pass "
        "(empty = the server's scheduler count)"),
    # -- autopilot ---------------------------------------------------
    "NOMAD_TPU_AUTOPILOT_INTERVAL": Knob(
        "0.05", "float",
        "autopilot tick interval (leader-side server-lifecycle loop)"),
    "NOMAD_TPU_AUTOPILOT_STABILIZATION": Knob(
        "0.25", "float",
        "how long a non-voter must stay healthy before promotion to "
        "voter"),
    "NOMAD_TPU_AUTOPILOT_LAG": Knob(
        "16", "int",
        "max log entries a server may trail the leader and still "
        "count as healthy"),
    "NOMAD_TPU_AUTOPILOT_REAP_AFTER": Knob(
        "1.0", "float",
        "seconds a gossip-FAILED server stays in the raft config "
        "before autopilot removes it"),
    # -- raft / fleet plumbing ---------------------------------------
    "NOMAD_TPU_FSYNC": Knob(
        "batch", "str",
        "WAL fsync policy: `always` | `batch` | `off`"),
    "NOMAD_TPU_SNAP_CHUNK": Knob(
        "262144", "int",
        "frame size (bytes) of the chunked InstallSnapshot stream"),
    "NOMAD_TPU_SNAP_WINDOW": Knob(
        "8", "int",
        "snapshot-stream frames buffered per peer (sender memory = "
        "window x chunk)"),
    "NOMAD_TPU_HEARTBEAT_BATCH_MS": Knob(
        "50", "float",
        "leader heartbeat-batcher flush interval (one "
        "`NodeHeartbeatBatch` raft entry per flush)"),
    "NOMAD_TPU_HB_PENDING_MAX": Knob(
        "8192", "int",
        "heartbeat-batcher pending cap; at the cap the writer forces "
        "a flush"),
    "NOMAD_TPU_INTEGRITY_INTERVAL": Knob(
        "2.0", "float",
        "seconds between leader `STATE_CHECKPOINT` proposals (replica "
        "digest votes); <= 0 disables the integrity plane"),
    "NOMAD_TPU_INTEGRITY_FULL_EVERY": Knob(
        "4", "int",
        "every Nth checkpoint full-walks all tables (ground truth for "
        "divergence conviction; between them digests are incremental)"),
    "NOMAD_TPU_FLEET_AGENTS": Knob(
        "10000", "int",
        "in-process client agents the `fleet_soak` bench cells "
        "register and heartbeat"),
    # -- overload control --------------------------------------------
    "NOMAD_TPU_DEFAULT_DEADLINE": Knob(
        "", "float",
        "ingress budget (s) when no `X-Nomad-Deadline` header; empty "
        "= no default deadline"),
    "NOMAD_TPU_ADMIT_RATE": Knob(
        "0", "float",
        "admission tokens/sec refilled per namespace (`0` = off)"),
    "NOMAD_TPU_ADMIT_BURST": Knob(
        "0", "float",
        "admission bucket capacity (`0` = 2x rate)"),
    "NOMAD_TPU_ADMIT_CONCURRENCY": Knob(
        "0", "int",
        "in-flight requests per namespace (`0` = off)"),
    "NOMAD_TPU_BROWNOUT_DEPTH": Knob(
        "256", "int",
        "proposal-queue depth at the brownout edge"),
    "NOMAD_TPU_BROWNOUT_LAG": Knob(
        "512", "int",
        "commit->apply lag (entries) at the brownout edge"),
    # -- event streaming ---------------------------------------------
    "NOMAD_TPU_SUB_QUEUE": Knob(
        "1024", "int",
        "per-subscriber event queue depth before the subscriber is "
        "marked lagging"),
    "NOMAD_TPU_EVENT_BUFFER": Knob(
        "256", "int",
        "retained event-broker ring size (catch-up window)"),
    "NOMAD_TPU_STREAM_HEARTBEAT": Knob(
        "1.0", "float",
        "blocking-stream heartbeat interval (s), per-request "
        "overridable"),
    # -- observability / fault injection -----------------------------
    "NOMAD_TPU_TRACE": Knob(
        "", "bool",
        "install a process-wide tracer at import (`1` to enable)"),
    "NOMAD_TPU_TRACE_SAMPLE": Knob(
        "1.0", "float",
        "trace sampling rate in [0, 1]"),
    "NOMAD_TPU_CHAOS": Knob(
        "", "str",
        "chaos-injection spec (`seed=42;rpc.drop=0.05;...`), empty = "
        "disabled"),
    # -- native library ----------------------------------------------
    "NOMAD_TPU_NATIVE_LIB": Knob(
        "", "str",
        "path override for the nomad_native shared library (empty = "
        "build-dir discovery)"),
    "NOMAD_TPU_NATIVE_BREAKER": Knob(
        "3", "int",
        "native-call circuit breaker: consecutive faults before "
        "falling back to pure-python"),
    # -- misc --------------------------------------------------------
    "NOMAD_TPU_ACL": Knob(
        "", "bool",
        "`1` enables ACL enforcement at boot (`server.enable_acl()`)"),
    "NOMAD_TPU_TEMPLATE_POLL_S": Knob(
        "0.5", "float",
        "task template re-render poll interval (s)"),
    "NOMAD_TPU_JAX_CACHE": Knob(
        "1", "bool",
        "`0` disables the persistent jax compilation cache"),
    "NOMAD_TPU_JAX_CACHE_DIR": Knob(
        "", "str",
        "persistent jax compilation cache root (empty = "
        "`<repo>/.jax_cache`)"),
}

_FALSE_STRINGS = ("", "0", "false", "no", "off")


def _raw(name: str, env: Optional[Mapping[str, str]]) -> tuple:
    try:
        knob = KNOBS[name]
    except KeyError:
        raise KeyError(
            f"unregistered knob {name!r}: declare it in "
            f"nomad_tpu/knobs.py KNOBS") from None
    src: Mapping[str, str] = os.environ if env is None else env
    val = src.get(name)
    if val is None or val == "":
        return None, knob
    return val, knob


def get_str(name: str, default: Optional[str] = None,
            env: Optional[Mapping[str, str]] = None) -> str:
    """The knob's raw string value ("" when unset and no default)."""
    raw, knob = _raw(name, env)
    if raw is not None:
        return raw
    return knob.default if default is None else default


def get_int(name: str, default: Optional[int] = None,
            env: Optional[Mapping[str, str]] = None) -> Optional[int]:
    """The knob as an int; `None` when unset with an empty registry
    default and no `default=` (knobs where empty means "auto")."""
    raw, knob = _raw(name, env)
    if raw is not None:
        return int(raw)
    if default is not None:
        return default
    return int(knob.default) if knob.default else None


def get_float(name: str, default: Optional[float] = None,
              env: Optional[Mapping[str, str]] = None) -> Optional[float]:
    """The knob as a float; `None` when unset with an empty registry
    default and no `default=`."""
    raw, knob = _raw(name, env)
    if raw is not None:
        return float(raw)
    if default is not None:
        return default
    return float(knob.default) if knob.default else None


def get_bool(name: str, default: Optional[bool] = None,
             env: Optional[Mapping[str, str]] = None) -> bool:
    """The knob as a bool: "", "0", "false", "no", "off" (any case)
    are false, anything else true; unset falls back to `default=` then
    the registry default."""
    raw, knob = _raw(name, env)
    if raw is None:
        if default is not None:
            return default
        raw = knob.default
    return raw.strip().lower() not in _FALSE_STRINGS


@contextlib.contextmanager
def override(name: str, value) -> Iterator[None]:
    """Scope an environment override of a registered knob to a block
    (`None` unsets).  Restores the prior state on exit."""
    if name not in KNOBS:
        raise KeyError(
            f"unregistered knob {name!r}: declare it in "
            f"nomad_tpu/knobs.py KNOBS")
    old = os.environ.get(name)
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


def markdown_table() -> str:
    """README knob table, one row per registered knob (the README
    copy is generated from here: ``python -m nomad_tpu.knobs``)."""
    rows = ["| knob | default | type | meaning |",
            "| --- | --- | --- | --- |"]
    for name, knob in KNOBS.items():
        default = f"`{knob.default}`" if knob.default else "unset"
        rows.append(f"| `{name}` | {default} | {knob.type} | "
                    f"{knob.doc} |")
    return "\n".join(rows)


if __name__ == "__main__":  # pragma: no cover - exercised via CLI
    print(markdown_table())
