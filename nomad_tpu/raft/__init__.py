"""Raft consensus layer (reference: vendored hashicorp/raft wired in
nomad/server.go:107-111 + the FSM in nomad/fsm.go).

The control plane's writes are replicated log entries: every mutation is a
(MessageType, payload) record appended to a Raft log and applied to each
server's StateStore by the NomadFSM — exactly the reference's
`nomadFSM.Apply` switch (nomad/fsm.go:211-313).  Leadership drives which
server runs the broker/workers/plan-applier (nomad/leader.go:277).
"""
from nomad_tpu.raft.fsm import MessageType, NomadFSM
from nomad_tpu.raft.log import LogEntry, LogStore, WALCorruptionError
from nomad_tpu.raft.meta import DurableMeta, MetaPersistError
from nomad_tpu.raft.node import (CONFIGURATION_MSG,
                                 ConfigurationInFlightError, NotLeaderError,
                                 RaftConfig, RaftNode)
from nomad_tpu.raft.snapshot import FileSnapshotStore
from nomad_tpu.raft.transport import InMemTransport

__all__ = [
    "MessageType", "NomadFSM", "LogEntry", "LogStore", "RaftNode",
    "RaftConfig", "NotLeaderError", "InMemTransport", "FileSnapshotStore",
    "DurableMeta", "MetaPersistError", "WALCorruptionError",
    "CONFIGURATION_MSG", "ConfigurationInFlightError",
]
