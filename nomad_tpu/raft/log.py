"""Raft write-ahead log (reference: raft-boltdb log store + raftInmem,
nomad/server.go:107-111).

In-memory list of entries with a crash-safe append-only file behind it so
a restarted server replays its log from disk (the BoltDB store's job in
the reference — BoltDB gives the reference checksummed pages and fsynced
commits for free; this store provides the same guarantees explicitly).

On-disk format (version 1): the file opens with an 8-byte magic
(``NTPUWAL1``, last byte = format version) followed by length-prefixed
records::

    [u32 payload_len][u32 crc32(payload)][payload]

where payload is the pickled op tuple ``("entry", index, term, type,
body)`` or ``("compact", index)``.  The length + CRC catch exactly the
crash-consistency failures Pillai et al. (OSDI 2014) show dominate real
storage bugs:

- a *torn tail* — the record extends past EOF or its checksum fails with
  nothing valid after it, i.e. what a crash mid-append leaves behind —
  is truncated with a warning and the store opens normally;
- *mid-stream corruption* — a bad record followed by valid ones — means
  committed history is damaged, and the store refuses to open
  (`WALCorruptionError`) rather than silently dropping entries; restore
  from a snapshot/peer instead.

Durability policy (``NOMAD_TPU_FSYNC``):

    always   fsync before ``append()`` returns
    batch    group commit (default): the appender blocks until a
             background syncer's fsync covers its record, so concurrent
             appends amortize one fsync (BoltDB-style group commit)
    off      never fsync — page cache only (dev/test)

Regardless of policy, ``append()`` only returns once the record is at
least in the OS page cache, and the Raft metadata store (term/vote,
``raft/meta.py``) always fsyncs — the policies here trade off *log*
durability, never election safety.

Legacy migration: a seed-era WAL (bare pickle stream) is detected by its
first byte (pickle's 0x80 opcode vs. the magic), parsed tolerating a
truncated/corrupt tail, and rewritten atomically in the new format on
first open; the original is kept at ``<path>.legacy``.

Chaos points (see nomad_tpu/chaos.py): ``disk.fsync_fail`` at every
fsync, ``disk.corrupt_read`` at record reads (CRC catches, reader
retries), ``disk.torn_write`` at `simulate_crash` (the power-loss hook
the durability soak drives).
"""
from __future__ import annotations

import io
import logging
import os
import pickle
import struct
import tempfile
import threading
import time
import zlib
from typing import List, Optional, Tuple

from nomad_tpu import chaos, knobs

log = logging.getLogger(__name__)

WAL_MAGIC = b"NTPUWAL1"
_HDR = struct.Struct("<II")
# a record length beyond this is treated as corruption, not data (the
# biggest real payloads — FSM snapshots — live in the snapshot store)
_MAX_RECORD = 1 << 30
# how far past a bad record _parse scans for a valid successor before
# declaring the damage a torn tail (bounds the O(n·m) resync probe)
_RESYNC_WINDOW = 1 << 20

FSYNC_POLICIES = ("always", "batch", "off")


class WALCorruptionError(RuntimeError):
    """Mid-stream WAL corruption: valid records exist past a damaged one,
    so truncating would drop committed history.  Restore from snapshot or
    re-join from peers instead of starting on a silently shortened log."""


def fsync_policy_from_env() -> str:
    pol = knobs.get_str("NOMAD_TPU_FSYNC").strip().lower()
    if pol not in FSYNC_POLICIES:
        raise ValueError(
            f"NOMAD_TPU_FSYNC={pol!r}: want one of {', '.join(FSYNC_POLICIES)}")
    return pol


def encode_record(payload: bytes) -> bytes:
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def fsync_dir(path: str) -> None:
    """fsync the directory containing `path` so a rename/create survives
    power loss (the step Pillai et al. found most often missing)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:           # some filesystems can't fsync directories
        pass
    finally:
        os.close(fd)


def _valid_record_at(data: bytes, off: int) -> bool:
    if off + _HDR.size > len(data):
        return False
    ln, crc = _HDR.unpack_from(data, off)
    end = off + _HDR.size + ln
    if ln > _MAX_RECORD or end > len(data):
        return False
    return zlib.crc32(data[off + _HDR.size:end]) == crc


def _read_payload(data: bytes, off: int, ln: int, crc: int) -> Optional[bytes]:
    """One record read with CRC verification.  A transient corrupt read
    (chaos `disk.corrupt_read`, or real bit rot between media and memory)
    fails the CRC and is retried once from the source."""
    for attempt in (0, 1):
        payload = data[off:off + ln]
        if attempt == 0 and chaos.active is not None \
                and payload and chaos.should("disk.corrupt_read"):
            payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
        if zlib.crc32(payload) == crc:
            return payload
        log.warning("wal: CRC mismatch reading record at offset %d "
                    "(attempt %d); retrying read", off, attempt + 1)
    return None


class LogEntry:
    __slots__ = ("index", "term", "msg_type", "payload")

    def __init__(self, index: int, term: int, msg_type: str, payload):
        self.index = index
        self.term = term
        self.msg_type = msg_type
        self.payload = payload

    def __repr__(self):
        return f"<LogEntry {self.index} t{self.term} {self.msg_type}>"


class LogStore:
    # wait-graph (nomad_tpu.analysis): locks whose JOB is to serialize
    # blocking I/O, with the reason they may be held across it
    _LOCK_BLOCKING_OK = {
        "_lock": "the WAL lock serializes append+fsync by design; "
                 "contending appenders need that durability ordering",
    }

    def __init__(self, path: Optional[str] = None,
                 fsync: Optional[str] = None):
        self._lock = threading.Lock()
        self._entries: List[LogEntry] = []
        self.first_index = 1           # index of _entries[0] if any
        self.path = path
        self.fsync_policy = fsync or fsync_policy_from_env()
        if self.fsync_policy not in FSYNC_POLICIES:
            raise ValueError(f"bad fsync policy {self.fsync_policy!r}")
        self._fh = None
        self._size = 0                 # bytes written (file offset)
        self._synced_size = 0          # bytes known durable (fsynced)
        self._sync_cv = threading.Condition()
        self._sync_stop = threading.Event()
        self._syncer: Optional[threading.Thread] = None
        if path:
            for op in self._load(path):
                self._replay(op)
            # buffering=0: writes reach the OS immediately, so the only
            # volatile window left is page cache → disk, which is exactly
            # what _synced_size / simulate_crash model
            self._fh = open(path, "ab", buffering=0)
            self._size = self._synced_size = os.path.getsize(path)
            if self.fsync_policy == "batch":
                self._syncer = threading.Thread(
                    target=self._sync_loop, name="wal-sync", daemon=True)
                self._syncer.start()

    # ------------------------------------------------------------- disk

    def _load(self, path: str) -> List[tuple]:
        """Read (and, where needed, repair or migrate) the WAL; returns
        the ops to replay.  Leaves the on-disk file valid new-format."""
        if not os.path.exists(path):
            self._create(path)
            return []
        with open(path, "rb") as fh:
            data = fh.read()
        if not data:
            self._create(path)
            return []
        if not data.startswith(WAL_MAGIC):
            return self._migrate_legacy(path, data)
        ops, valid_size = self._parse(data, path)
        if valid_size < len(data):
            log.warning(
                "wal: %s has a torn tail (%d trailing bytes after a crash "
                "mid-append); truncating to last valid record at %d",
                path, len(data) - valid_size, valid_size)
            with open(path, "r+b") as fh:
                fh.truncate(valid_size)
                fh.flush()
                os.fsync(fh.fileno())
        return ops

    def _create(self, path: str) -> None:
        """New WAL: the magic header is fsynced (file and directory) at
        creation regardless of policy, so the file itself — the restart
        anchor — always survives power loss."""
        with open(path, "wb") as fh:
            fh.write(WAL_MAGIC)
            fh.flush()
            os.fsync(fh.fileno())
        fsync_dir(path)

    def _parse(self, data: bytes, path: str) -> Tuple[List[tuple], int]:
        """Walk new-format records; returns (ops, valid_prefix_size).
        Raises WALCorruptionError on mid-stream damage."""
        ops: List[tuple] = []
        off = len(WAL_MAGIC)
        while off < len(data):
            if off + _HDR.size > len(data):
                return ops, off                      # torn header
            ln, crc = _HDR.unpack_from(data, off)
            body_off = off + _HDR.size
            end = body_off + ln
            if ln > _MAX_RECORD or end > len(data):
                # implausible/overrunning length: unreadable past here —
                # torn tail unless a valid record resyncs further on
                self._refuse_if_midstream(data, body_off, path, off)
                return ops, off
            payload = _read_payload(data, body_off, ln, crc)
            if payload is None:
                self._refuse_if_midstream(data, end, path, off)
                return ops, off
            try:
                op = pickle.loads(payload)
            except Exception:                        # noqa: BLE001
                self._refuse_if_midstream(data, end, path, off)
                return ops, off
            ops.append(op)
            off = end
        return ops, off

    @staticmethod
    def _refuse_if_midstream(data: bytes, scan_from: int, path: str,
                             bad_off: int) -> None:
        """A bad record followed by a parseable one is not a torn tail —
        committed history is damaged and truncation would lose it."""
        limit = min(len(data), scan_from + _RESYNC_WINDOW)
        for cand in range(max(scan_from, len(WAL_MAGIC)), limit):
            if _valid_record_at(data, cand):
                raise WALCorruptionError(
                    f"{path}: corrupt record at offset {bad_off} is "
                    f"followed by valid records (next at {cand}); "
                    f"refusing to truncate committed history — restore "
                    f"this member from a snapshot or a peer")

    def _migrate_legacy(self, path: str, data: bytes) -> List[tuple]:
        """Seed-format WAL (bare pickle stream): parse tolerating a
        truncated/corrupt tail, rewrite atomically in the new format."""
        ops: List[tuple] = []
        fh = io.BytesIO(data)
        while True:
            try:
                rec = pickle.load(fh)
            except EOFError:
                break
            except (pickle.UnpicklingError, AttributeError, ValueError,
                    IndexError, TypeError) as exc:
                log.warning(
                    "wal: dropping corrupt/truncated legacy tail of %s "
                    "at offset %d (%s)", path, fh.tell(), exc)
                break
            ops.append(tuple(rec))
        log.warning("wal: migrating legacy pickle WAL %s (%d records) to "
                    "checksummed format; original kept at %s.legacy",
                    path, len(ops), path)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   prefix=".wal-migrate-")
        with os.fdopen(fd, "wb") as out:
            out.write(WAL_MAGIC)
            for op in ops:
                out.write(encode_record(
                    pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL)))
            out.flush()
            os.fsync(out.fileno())
        os.replace(path, path + ".legacy")
        os.replace(tmp, path)
        fsync_dir(path)
        return ops

    def _replay(self, op: tuple) -> None:
        if op and op[0] == "entry":
            _, index, term, msg_type, payload = op
            self._truncate_from(index)
            if self._entries and index != self._entries[-1].index + 1:
                # a hole in the sequence is NOT a torn tail — every record
                # here passed its CRC.  The entries after the hole are
                # unreachable by index, so starting up would silently
                # misattribute state; refuse like any mid-stream damage.
                raise WALCorruptionError(
                    f"{self.path}: log gap — entry {index} follows "
                    f"{self._entries[-1].index}")
            if not self._entries:
                self.first_index = index
            self._entries.append(LogEntry(index, term, msg_type, payload))
        elif op and op[0] == "compact":
            self._compact_to(op[1])
        else:
            raise WALCorruptionError(
                f"{self.path}: unknown WAL record kind {op[:1]!r}")

    def _persist(self, op: tuple) -> Optional[int]:
        """Write one record (caller holds self._lock); returns the file
        offset the record ends at, for _wait_durable."""
        if self._fh is None:
            return None
        rec = encode_record(
            pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL))
        self._fh.write(rec)
        self._size += len(rec)
        return self._size

    # --------------------------------------------------------- durability

    def _fsync_once(self) -> bool:
        try:
            if chaos.active is not None and chaos.should("disk.fsync_fail"):
                raise OSError("chaos: injected fsync failure")
            os.fsync(self._fh.fileno())
            return True
        except (OSError, ValueError, AttributeError):
            log.warning("wal: fsync failed; will retry", exc_info=True)
            return False

    def _sync_loop(self) -> None:
        """Group-commit syncer: one fsync covers every record written
        before it started; appenders blocked in _wait_durable wake when
        _synced_size passes their offset."""
        while not self._sync_stop.is_set():
            with self._sync_cv:
                while self._synced_size >= self._size \
                        and not self._sync_stop.is_set():
                    self._sync_cv.wait(0.05)
                if self._sync_stop.is_set():
                    return
            target = self._size
            ok = self._fsync_once()
            with self._sync_cv:
                if ok:
                    self._synced_size = max(self._synced_size, target)
                self._sync_cv.notify_all()
            if not ok:
                time.sleep(0.001)

    def _wait_durable(self, want: Optional[int]) -> None:
        """Block until the WAL is durable through offset `want` under the
        configured policy.  Must be called WITHOUT self._lock held."""
        if want is None or self._fh is None or self.fsync_policy == "off":
            return
        if self.fsync_policy == "always":
            for _ in range(3):
                if self._fsync_once():
                    with self._sync_cv:
                        self._synced_size = max(self._synced_size, want)
                    return
            log.warning("wal: giving up fsync after retries; record at "
                        "offset %d is page-cache only", want)
            return
        deadline = time.monotonic() + 5.0
        with self._sync_cv:
            self._sync_cv.notify_all()       # wake the syncer
            while self._synced_size < want \
                    and not self._sync_stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning("wal: group-commit fsync stalled; record "
                                "at offset %d is page-cache only", want)
                    return
                self._sync_cv.wait(min(remaining, 0.05))

    def sync(self) -> None:
        """Force the whole WAL durable now (used by close)."""
        with self._lock:
            want = self._size if self._fh is not None else None
        if want is not None and self._fsync_once():
            with self._sync_cv:
                self._synced_size = max(self._synced_size, want)

    def _stop_syncer(self) -> None:
        self._sync_stop.set()
        with self._sync_cv:
            self._sync_cv.notify_all()
        if self._syncer is not None:
            self._syncer.join(2.0)
            self._syncer = None

    def simulate_crash(self) -> None:
        """Power-loss simulation (the durability soak's kill switch):
        everything past the last fsync is lost, and an in-flight append
        may leave a partial record behind (chaos `disk.torn_write`).
        The store is unusable afterwards — reopen from `path`."""
        with self._lock:
            if self._fh is None:
                return
            self._stop_syncer()
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
            keep = self._synced_size
            size = os.path.getsize(self.path)
            if size > keep and chaos.should("disk.torn_write"):
                reg = chaos.active
                frac = reg.uniform() if reg is not None else 0.5
                torn = keep + max(1, int((size - keep) * frac))
                keep = min(torn, size - 1)
                log.warning("wal: simulated torn write — %s keeps %d of "
                            "%d bytes (partial tail record)",
                            self.path, keep, size)
            with open(self.path, "r+b") as fh:
                fh.truncate(max(keep, len(WAL_MAGIC)))
                fh.flush()
                os.fsync(fh.fileno())

    # ------------------------------------------------------------- core

    def _truncate_from(self, index: int) -> None:
        """Drop entries at >= index (conflict resolution)."""
        keep = index - self.first_index
        if keep < len(self._entries):
            del self._entries[max(keep, 0):]

    def _compact_to(self, index: int) -> None:
        drop = index - self.first_index + 1
        if drop > 0:
            del self._entries[:drop]
            self.first_index = index + 1

    def _append_locked(self, e: LogEntry) -> Optional[int]:
        self._truncate_from(e.index)
        if self._entries and e.index != self._entries[-1].index + 1:
            # refuse to create a hole: entries list is positional, so a
            # gapped append would misindex every later lookup and write a
            # WAL that cannot be replayed (see _replay's gap check)
            raise ValueError(
                f"non-contiguous append: entry {e.index} after "
                f"{self._entries[-1].index}")
        if not self._entries:
            self.first_index = e.index
        self._entries.append(e)
        return self._persist(("entry", e.index, e.term, e.msg_type,
                              e.payload))

    def append(self, e: LogEntry) -> None:
        with self._lock:
            want = self._append_locked(e)
        self._wait_durable(want)

    def append_batch(self, entries: List[LogEntry]) -> None:
        """Append several entries with ONE durability wait — the follower
        AppendEntries path, where per-entry fsync waits would serialize
        catch-up replication."""
        if not entries:
            return
        want = None
        with self._lock:
            for e in entries:
                want = self._append_locked(e)
        self._wait_durable(want)

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            i = index - self.first_index
            if 0 <= i < len(self._entries):
                return self._entries[i]
            return None

    def entries_of_type(self, msg_type: str) -> List[LogEntry]:
        """All live (uncompacted) entries of one message type, in index
        order — the configuration-recovery scan at node boot."""
        with self._lock:
            return [e for e in self._entries if e.msg_type == msg_type]

    def entries_from(self, index: int, limit: int = 64) -> List[LogEntry]:
        with self._lock:
            i = index - self.first_index
            if i < 0:
                return []          # compacted away: caller must snapshot
            return self._entries[i:i + limit]

    def term_at(self, index: int) -> int:
        e = self.get(index)
        return e.term if e is not None else 0

    @property
    def last_index(self) -> int:
        with self._lock:
            if not self._entries:
                return self.first_index - 1
            return self._entries[-1].index

    @property
    def last_term(self) -> int:
        with self._lock:
            return self._entries[-1].term if self._entries else 0

    def compact(self, through_index: int) -> None:
        """Discard entries ≤ through_index (they live in a snapshot now)."""
        with self._lock:
            self._compact_to(through_index)
            want = self._persist(("compact", through_index))
        self._wait_durable(want)

    def close(self) -> None:
        self._stop_syncer()
        with self._lock:
            if self._fh is not None:
                if self.fsync_policy != "off":
                    self._fsync_once()
                try:
                    self._fh.close()
                finally:
                    self._fh = None
