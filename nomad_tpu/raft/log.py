"""Raft log store (reference: raft-boltdb log store + raftInmem,
nomad/server.go:107-111).

In-memory list of entries with an optional append-only file behind it so a
restarted server replays its log from disk (the BoltDB store's job in the
reference).  Entries before `first_index` have been compacted into a
snapshot.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import List, Optional


class LogEntry:
    __slots__ = ("index", "term", "msg_type", "payload")

    def __init__(self, index: int, term: int, msg_type: str, payload):
        self.index = index
        self.term = term
        self.msg_type = msg_type
        self.payload = payload

    def __repr__(self):
        return f"<LogEntry {self.index} t{self.term} {self.msg_type}>"


class LogStore:
    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._entries: List[LogEntry] = []
        self.first_index = 1           # index of _entries[0] if any
        self.path = path
        self._fh = None
        if path:
            self._load(path)
            self._fh = open(path, "ab")

    # ------------------------------------------------------------- disk

    def _load(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as fh:
            while True:
                try:
                    rec = pickle.load(fh)
                except EOFError:
                    break
                if rec[0] == "entry":
                    _, index, term, msg_type, payload = rec
                    self._truncate_from(index)
                    self._entries.append(LogEntry(index, term, msg_type, payload))
                elif rec[0] == "compact":
                    self._compact_to(rec[1])

    def _persist(self, e: LogEntry) -> None:
        if self._fh is not None:
            pickle.dump(("entry", e.index, e.term, e.msg_type, e.payload),
                        self._fh)
            self._fh.flush()

    # ------------------------------------------------------------- core

    def _truncate_from(self, index: int) -> None:
        """Drop entries at >= index (conflict resolution)."""
        keep = index - self.first_index
        if keep < len(self._entries):
            del self._entries[max(keep, 0):]

    def _compact_to(self, index: int) -> None:
        drop = index - self.first_index + 1
        if drop > 0:
            del self._entries[:drop]
            self.first_index = index + 1

    def append(self, e: LogEntry) -> None:
        with self._lock:
            self._truncate_from(e.index)
            if not self._entries:
                self.first_index = e.index
            self._entries.append(e)
            self._persist(e)

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            i = index - self.first_index
            if 0 <= i < len(self._entries):
                return self._entries[i]
            return None

    def entries_from(self, index: int, limit: int = 64) -> List[LogEntry]:
        with self._lock:
            i = index - self.first_index
            if i < 0:
                return []          # compacted away: caller must snapshot
            return self._entries[i:i + limit]

    def term_at(self, index: int) -> int:
        e = self.get(index)
        return e.term if e is not None else 0

    @property
    def last_index(self) -> int:
        with self._lock:
            if not self._entries:
                return self.first_index - 1
            return self._entries[-1].index

    @property
    def last_term(self) -> int:
        with self._lock:
            return self._entries[-1].term if self._entries else 0

    def compact(self, through_index: int) -> None:
        """Discard entries ≤ through_index (they live in a snapshot now)."""
        with self._lock:
            self._compact_to(through_index)
            if self._fh is not None:
                pickle.dump(("compact", through_index), self._fh)
                self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
