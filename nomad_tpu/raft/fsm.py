"""Replicated state machine (reference: nomad/fsm.go).

`NomadFSM.apply` is the message-type switch (`nomadFSM.Apply`
nomad/fsm.go:211-313) mapping log entries onto StateStore writes at the
entry's Raft index.  `snapshot`/`restore` persist the full store
(`nomadFSM.Snapshot/Restore`, same file) for log compaction and server
checkpoint/resume.

Leader-side hooks: when an eval lands in the store on the leader, it is
handed to the EvalBroker / BlockedEvals trackers (the reference FSM holds
the broker and enqueues when leadership is established — fsm.go eval
apply + leader.go:572 restore path).
"""
from __future__ import annotations

import pickle
from collections import defaultdict
from typing import Dict, Optional

from nomad_tpu.state.store import AppliedPlanResults, JobSummary, StateStore


class MessageType:
    """Log entry types (reference: structs.MessageType constants,
    nomad/structs/structs.go:87-150)."""
    NODE_REGISTER = "NodeRegisterRequest"
    NODE_DEREGISTER = "NodeDeregisterRequest"
    NODE_UPDATE_STATUS = "NodeUpdateStatusRequest"
    NODE_HEARTBEAT_BATCH = "NodeHeartbeatBatchRequest"
    NODE_FINGERPRINT_BATCH = "NodeFingerprintBatchRequest"
    NODE_UPDATE_DRAIN = "NodeUpdateDrainRequest"
    NODE_UPDATE_ELIGIBILITY = "NodeUpdateEligibilityRequest"
    JOB_REGISTER = "JobRegisterRequest"
    JOB_DEREGISTER = "JobDeregisterRequest"
    JOB_STABILITY = "JobStabilityRequest"
    EVAL_UPDATE = "EvalUpdateRequest"
    EVAL_DELETE = "EvalDeleteRequest"
    ALLOC_UPDATE = "AllocUpdateRequest"
    ALLOC_CLIENT_UPDATE = "AllocClientUpdateRequest"
    ALLOC_UPDATE_DESIRED_TRANSITION = "AllocUpdateDesiredTransitionRequest"
    APPLY_PLAN_RESULTS = "ApplyPlanResultsRequest"
    DEPLOYMENT_UPSERT = "DeploymentUpsertRequest"
    DEPLOYMENT_DELETE = "DeploymentDeleteRequest"
    SCHEDULER_CONFIG = "SchedulerConfigRequest"
    NAMESPACE_UPSERT = "NamespaceUpsertRequest"
    NAMESPACE_DELETE = "NamespaceDeleteRequest"
    QUOTA_SPEC_UPSERT = "QuotaSpecUpsertRequest"
    QUOTA_SPEC_DELETE = "QuotaSpecDeleteRequest"
    CSI_VOLUME_REGISTER = "CSIVolumeRegisterRequest"
    CSI_VOLUME_DEREGISTER = "CSIVolumeDeregisterRequest"
    CSI_VOLUME_CLAIM = "CSIVolumeClaimRequest"
    ACL_POLICY_UPSERT = "ACLPolicyUpsertRequest"
    ACL_POLICY_DELETE = "ACLPolicyDeleteRequest"
    ACL_TOKEN_UPSERT = "ACLTokenUpsertRequest"
    ACL_TOKEN_DELETE = "ACLTokenDeleteRequest"
    SCALING_EVENT = "ScalingEventRequest"
    SERVICE_REGISTER = "ServiceRegistrationUpsertRequest"
    SERVICE_DEREGISTER = "ServiceRegistrationDeleteRequest"
    NOOP = "Noop"                  # leadership-establishment barrier entry
    STATE_CHECKPOINT = "StateCheckpointRequest"  # integrity digest stamp


# Snapshot tables each message type may touch, for the integrity plane's
# incremental digests (raft/integrity.py): a checkpoint recomputes only
# the tables dirtied since the last one.  Entries are SUPERSETS of what
# the handlers' store calls mutate — over-declaring costs a recompute,
# and the periodic full walk (ground truth) plus the conviction-on-full
# rule in IntegrityTracker.evaluate mean even an under-declared entry
# can delay detection but never convict a healthy replica.  Types not
# listed here (periphery `extra` handlers) dirty EVERYTHING.
_APPLY_TOUCHES = {
    MessageType.NODE_REGISTER: ("nodes", "csi_plugins"),
    MessageType.NODE_DEREGISTER: ("nodes", "csi_plugins"),
    MessageType.NODE_UPDATE_STATUS: ("nodes",),
    MessageType.NODE_HEARTBEAT_BATCH: ("nodes",),
    MessageType.NODE_FINGERPRINT_BATCH: ("nodes",),
    MessageType.NODE_UPDATE_DRAIN: ("nodes",),
    MessageType.NODE_UPDATE_ELIGIBILITY: ("nodes",),
    MessageType.JOB_REGISTER:
        ("jobs", "job_versions", "job_summaries", "namespaces"),
    MessageType.JOB_DEREGISTER:
        ("jobs", "job_versions", "job_summaries", "scaling_events",
         "deployments", "evals", "allocs", "services", "quota_usage"),
    MessageType.JOB_STABILITY: ("jobs", "job_versions"),
    MessageType.EVAL_UPDATE: ("evals",),
    MessageType.EVAL_DELETE:
        ("evals", "allocs", "job_summaries", "quota_usage", "services"),
    MessageType.ALLOC_UPDATE:
        ("allocs", "job_summaries", "quota_usage", "services",
         "deployments"),
    MessageType.ALLOC_CLIENT_UPDATE:
        ("allocs", "job_summaries", "quota_usage", "services",
         "deployments"),
    MessageType.ALLOC_UPDATE_DESIRED_TRANSITION:
        ("allocs", "job_summaries", "quota_usage", "services",
         "deployments", "evals"),
    MessageType.APPLY_PLAN_RESULTS:
        ("allocs", "evals", "deployments", "job_summaries", "quota_usage",
         "applied_plan_ids", "services"),
    MessageType.DEPLOYMENT_UPSERT:
        ("deployments", "jobs", "job_versions", "allocs", "evals",
         "job_summaries"),
    MessageType.DEPLOYMENT_DELETE: ("deployments",),
    MessageType.SCHEDULER_CONFIG: ("scheduler_config",),
    MessageType.NAMESPACE_UPSERT: ("namespaces",),
    MessageType.NAMESPACE_DELETE: ("namespaces",),
    MessageType.QUOTA_SPEC_UPSERT: ("quota_specs", "quota_usage"),
    MessageType.QUOTA_SPEC_DELETE: ("quota_specs", "quota_usage"),
    MessageType.CSI_VOLUME_REGISTER: ("csi_volumes", "csi_plugins"),
    MessageType.CSI_VOLUME_DEREGISTER: ("csi_volumes", "csi_plugins"),
    MessageType.CSI_VOLUME_CLAIM: ("csi_volumes", "csi_plugins"),
    MessageType.ACL_POLICY_UPSERT: ("acl_policies",),
    MessageType.ACL_POLICY_DELETE: ("acl_policies",),
    MessageType.ACL_TOKEN_UPSERT: ("acl_tokens",),
    MessageType.ACL_TOKEN_DELETE: ("acl_tokens",),
    MessageType.SCALING_EVENT: ("scaling_events",),
    MessageType.SERVICE_REGISTER: ("services",),
    MessageType.SERVICE_DEREGISTER: ("services",),
    MessageType.NOOP: (),
    MessageType.STATE_CHECKPOINT: (),
    "RaftConfiguration": (),
}


class NomadFSM:
    """Applies committed log entries to a StateStore.

    `hooks` is the owning Server (or None): after an EVAL_UPDATE commit on
    the leader, pending evals are enqueued in the broker and blocked evals
    registered with the BlockedEvals tracker.
    """

    def __init__(self, store: StateStore, hooks=None):
        self.store = store
        self.hooks = hooks
        self._dispatch = {
            MessageType.NODE_REGISTER: self._apply_node_register,
            MessageType.NODE_DEREGISTER: self._apply_node_deregister,
            MessageType.NODE_UPDATE_STATUS: self._apply_node_update_status,
            MessageType.NODE_HEARTBEAT_BATCH:
                self._apply_node_heartbeat_batch,
            MessageType.NODE_FINGERPRINT_BATCH:
                self._apply_node_fingerprint_batch,
            MessageType.NODE_UPDATE_DRAIN: self._apply_node_update_drain,
            MessageType.NODE_UPDATE_ELIGIBILITY: self._apply_node_eligibility,
            MessageType.JOB_REGISTER: self._apply_job_register,
            MessageType.JOB_DEREGISTER: self._apply_job_deregister,
            MessageType.JOB_STABILITY: self._apply_job_stability,
            MessageType.EVAL_UPDATE: self._apply_eval_update,
            MessageType.EVAL_DELETE: self._apply_eval_delete,
            MessageType.ALLOC_UPDATE: self._apply_alloc_update,
            MessageType.ALLOC_CLIENT_UPDATE: self._apply_alloc_client_update,
            MessageType.ALLOC_UPDATE_DESIRED_TRANSITION:
                self._apply_alloc_desired_transition,
            MessageType.APPLY_PLAN_RESULTS: self._apply_plan_results,
            MessageType.DEPLOYMENT_UPSERT: self._apply_deployment_upsert,
            MessageType.DEPLOYMENT_DELETE: self._apply_deployment_delete,
            MessageType.SCHEDULER_CONFIG: self._apply_scheduler_config,
            MessageType.CSI_VOLUME_REGISTER: self._apply_csi_volume_register,
            MessageType.CSI_VOLUME_DEREGISTER: self._apply_csi_volume_deregister,
            MessageType.CSI_VOLUME_CLAIM: self._apply_csi_volume_claim,
            MessageType.NAMESPACE_UPSERT: self._apply_namespace_upsert,
            MessageType.NAMESPACE_DELETE: self._apply_namespace_delete,
            MessageType.QUOTA_SPEC_UPSERT: self._apply_quota_spec_upsert,
            MessageType.QUOTA_SPEC_DELETE: self._apply_quota_spec_delete,
            MessageType.ACL_POLICY_UPSERT: self._apply_acl_policy_upsert,
            MessageType.ACL_POLICY_DELETE: self._apply_acl_policy_delete,
            MessageType.ACL_TOKEN_UPSERT: self._apply_acl_token_upsert,
            MessageType.ACL_TOKEN_DELETE: self._apply_acl_token_delete,
            MessageType.SCALING_EVENT: self._apply_scaling_event,
            MessageType.SERVICE_REGISTER: self._apply_service_register,
            MessageType.SERVICE_DEREGISTER: self._apply_service_deregister,
            MessageType.NOOP: lambda index, p: None,
            # integrity checkpoints are deterministic no-ops in the FSM:
            # the digest walk happens in the raft apply loop (outside the
            # replicated-write cone), and the entry is stamped at propose
            # time so the FSM never reads the clock
            MessageType.STATE_CHECKPOINT: lambda index, p: None,
            # cluster configuration entries (Raft §4.1) are consumed by
            # the raft layer on append; the FSM treats them as no-ops so
            # replicas stay byte-identical across membership changes
            "RaftConfiguration": lambda index, p: None,
        }
        # optional table handlers registered by periphery subsystems
        self.extra: Dict[str, callable] = {}
        self.snapshot_extra: Dict[str, callable] = {}
        self.restore_extra: Dict[str, callable] = {}
        # integrity plane's incremental-digest hook: called after each
        # apply with the tables the entry may have touched (None = all)
        self.dirty_hook = None

    # ------------------------------------------------------------- apply

    def apply(self, index: int, msg_type: str, payload: dict) -> None:
        fn = self._dispatch.get(msg_type) or self.extra.get(msg_type)
        if fn is None:
            raise ValueError(f"unknown FSM message type {msg_type!r}")
        fn(index, payload)
        hook = self.dirty_hook
        if hook is not None:
            hook(_APPLY_TOUCHES.get(msg_type))

    # --- nodes

    def _apply_node_register(self, index, p):
        # copy at the consensus boundary: in cluster mode the payload
        # arrives pickled, but dev mode shares objects with the caller —
        # a caller later mutating its Node must not bypass the FSM
        # (the aliasing would desync the dense matrix from the store)
        import copy as _copy
        self.store.upsert_node(index, _copy.deepcopy(p["node"]))
        hooks = self.hooks
        if hooks is not None and getattr(hooks, "leader", False):
            # TTL timers live on the leader (nomad/heartbeat.go:56); track
            # here so registrations forwarded from followers get a timer
            hooks.heartbeats.heartbeat(p["node"].id)

    def _apply_node_deregister(self, index, p):
        self.store.delete_node(index, p["node_id"])

    def _apply_node_update_status(self, index, p):
        self.store.update_node_status(
            index, p["node_id"], p["status"], p.get("updated_at", 0.0))

    def _apply_node_heartbeat_batch(self, index, p):
        # the heartbeat coalescer flushes one entry per tick: revivals,
        # expiries and liveness stamps for a whole fleet batch land in a
        # single store write (updated_at was stamped at propose time —
        # the FSM never reads the clock)
        self.store.update_node_statuses_many(index, p["updates"])

    def _apply_node_fingerprint_batch(self, index, p):
        # device/attribute re-fingerprint deltas coalesce through the
        # HeartbeatBatcher: one entry per flush tick carries a whole
        # fleet's fingerprint churn instead of one full Node.Register
        # per change (stamped at propose time, like the heartbeat batch)
        self.store.update_node_fingerprints_many(index, p["updates"])

    def _apply_node_update_drain(self, index, p):
        self.store.update_node_drain(
            index, p["node_id"], p.get("drain_strategy"),
            p.get("mark_eligible", False))

    def _apply_node_eligibility(self, index, p):
        self.store.update_node_eligibility(
            index, p["node_id"], p["eligibility"])

    # --- jobs

    def _apply_job_register(self, index, p):
        self.store.upsert_job(index, p["job"])

    def _apply_job_deregister(self, index, p):
        if p.get("purge"):
            self.store.delete_job(index, p["namespace"], p["job_id"])
        else:
            job = self.store.job_by_id(p["namespace"], p["job_id"])
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.store.upsert_job(index, stopped)

    def _apply_job_stability(self, index, p):
        self.store.mark_job_stability(
            index, p["namespace"], p["job_id"], p["version"], p["stable"])

    # --- evals

    def _apply_eval_update(self, index, p):
        evals = p["evals"]
        self.store.upsert_evals(index, evals)
        hooks = self.hooks
        if hooks is not None and getattr(hooks, "leader", False):
            for ev in evals:
                if ev.should_enqueue():
                    hooks.broker.enqueue(ev.copy())
                elif ev.should_block():
                    hooks.blocked_evals.block(ev.copy())

    def _apply_eval_delete(self, index, p):
        self.store.delete_eval(index, p["eval_ids"], p.get("alloc_ids", ()))

    # --- allocs

    def _apply_alloc_update(self, index, p):
        self.store.upsert_allocs(index, p["allocs"])

    def _apply_alloc_client_update(self, index, p):
        self.store.update_allocs_from_client(index, p["allocs"])

    def _apply_alloc_desired_transition(self, index, p):
        # reference AllocUpdateDesiredTransitionRequest carries Evals so
        # the transition and its follow-up eval commit atomically — a
        # partition between two entries can otherwise strand stopped
        # allocs with no eval to replace them
        self.store.upsert_allocs(index, p["allocs"])
        evals = p.get("evals")
        if evals:
            self._apply_eval_update(index, {"evals": evals})

    # --- plans / deployments / config

    def _apply_plan_results(self, index, p):
        # the applier coalesces adjacent plans into one log entry: a
        # list payload commits the whole batch in one store write
        results = p["results"]
        if isinstance(results, list):
            self.store.upsert_plan_results_many(index, results)
        else:
            self.store.upsert_plan_results(index, results)

    def _apply_deployment_upsert(self, index, p):
        self.store.upsert_deployment(index, p["deployment"])

    def _apply_deployment_delete(self, index, p):
        self.store.delete_deployment(index, p["deployment_id"])

    def _apply_scheduler_config(self, index, p):
        self.store.set_scheduler_config(index, p["config"])
        # the broker's fair-dequeue knobs are live-tunable: push the
        # replicated config into the leader's broker on apply
        hooks = self.hooks
        if hooks is not None and getattr(hooks, "leader", False):
            hooks.broker.set_fair_config(p["config"])

    # ------------------------------------------------------------- snapshot

    # --- namespaces / ACL

    def _apply_csi_volume_register(self, index, p):
        self.store.upsert_csi_volume(index, p["volume"])

    def _apply_csi_volume_deregister(self, index, p):
        self.store.deregister_csi_volume(
            index, p["namespace"], p["volume_id"], p.get("force", False))

    def _apply_csi_volume_claim(self, index, p):
        self.store.csi_volume_claim(
            index, p["namespace"], p["volume_id"], p["claim"])

    def _apply_namespace_upsert(self, index, p):
        prev = self.store.namespace(p["name"])
        self.store.upsert_namespace(index, p["name"],
                                    p.get("description", ""),
                                    p.get("quota", ""))
        # re-pointing a namespace at a different (or no) quota spec can
        # free evals blocked on the OLD spec; one-shot unblock on the
        # leader, mirroring the class-eligibility unblock path
        hooks = self.hooks
        if hooks is not None and getattr(hooks, "leader", False):
            old_quota = getattr(prev, "quota", "") if prev else ""
            if old_quota and old_quota != p.get("quota", ""):
                hooks.blocked_evals.unblock_quota(old_quota, index)

    def _apply_namespace_delete(self, index, p):
        self.store.delete_namespace(index, p["name"])

    def _apply_quota_spec_upsert(self, index, p):
        self.store.upsert_quota_spec(index, p["spec"])
        # a raised quota must rescue evals blocked on it (satellite of
        # the PR 9 class-eligibility fix: quota-keyed one-shot unblock)
        hooks = self.hooks
        if hooks is not None and getattr(hooks, "leader", False):
            hooks.blocked_evals.unblock_quota(p["spec"].name, index)

    def _apply_quota_spec_delete(self, index, p):
        self.store.delete_quota_spec(index, p["name"])

    def _apply_acl_policy_upsert(self, index, p):
        self.store.upsert_acl_policy(index, p["policy"])

    def _apply_acl_policy_delete(self, index, p):
        self.store.delete_acl_policy(index, p["name"])

    def _apply_acl_token_upsert(self, index, p):
        # replicated one-time-bootstrap invariant: a bootstrap-minted
        # management token is dropped if one already exists, so the check
        # is deterministic across the cluster (reference: ACL bootstrap
        # goes through Raft with a reset index guard)
        if p.get("bootstrap"):
            tok = p["token"]
            if any(t.type == "management"
                   for t in self.store.acl_tokens()
                   if t.accessor_id != tok.accessor_id):
                return
        self.store.upsert_acl_token(index, p["token"])

    def _apply_acl_token_delete(self, index, p):
        self.store.delete_acl_token(index, p["accessor_id"])

    def _apply_scaling_event(self, index, p):
        self.store.upsert_scaling_event(
            index, p["namespace"], p["job_id"], p["group"], p["event"])

    def _apply_service_register(self, index, p):
        self.store.upsert_service_registrations(index, p["services"])

    def _apply_service_deregister(self, index, p):
        self.store.delete_service_registrations(
            index, p.get("ids"), alloc_id=p.get("alloc_id"))

    def snapshot_tables(self) -> dict:
        """The snapshot record dict BEFORE pickling — the integrity
        plane digests these tables directly (state/digest.py) so the
        runtime digest and the snapshot bytes share one encoding."""
        s = self.store
        with s._lock:
            data = {
                "latest_index": s.latest_index,
                "nodes": list(s._nodes.values()),
                "jobs": dict(s._jobs),
                "job_versions": {k: list(v) for k, v in s._job_versions.items()},
                "evals": list(s._evals.values()),
                "allocs": list(s._allocs.values()),
                "deployments": list(s._deployments.values()),
                "job_summaries": dict(s._job_summaries),
                "scheduler_config": s.scheduler_config,
                "namespaces": dict(s._namespaces),
                "quota_specs": dict(s._quota_specs),
                # usage is restored verbatim (not rebuilt): entry
                # creation ORDER is part of the replicated table's
                # byte-identity, and a rebuild from the alloc list could
                # recreate zeroed-then-repopulated entries out of order
                "quota_usage": {k: dict(v)
                                for k, v in s._quota_usage.items()},
                "acl_policies": dict(s._acl_policies),
                "acl_tokens": list(s._acl_tokens.values()),
                "csi_volumes": dict(s._csi_volumes),
                "csi_plugins": dict(s._csi_plugins),
                "scaling_events": {k: list(v) for k, v in
                                   s._scaling_events.items()},
                "services": list(s._services.values()),
                "applied_plan_ids": list(s._applied_plan_ids),
                "extra": {name: fn() for name, fn in
                          getattr(self, "snapshot_extra", {}).items()},
            }
        return data

    def snapshot(self) -> bytes:
        """Serialize the full store (reference nomadFSM.Snapshot →
        nomadSnapshot.Persist, nomad/fsm.go)."""
        return pickle.dumps(self.snapshot_tables())

    def restore(self, blob: bytes) -> None:
        """Rebuild the store from a snapshot (reference nomadFSM.Restore).
        Indexes, summaries and the dense ClusterMatrix are all restored."""
        from nomad_tpu.encode import ClusterMatrix

        data = pickle.loads(blob)
        s = self.store
        with s._lock:
            s._nodes = {n.id: n for n in data["nodes"]}
            s._jobs = dict(data["jobs"])
            s._job_versions = defaultdict(list)
            for k, v in data["job_versions"].items():
                s._job_versions[k] = list(v)
            s._evals = {e.id: e for e in data["evals"]}
            s._allocs = {}
            s._allocs_by_job = defaultdict(set)
            s._allocs_by_node = defaultdict(set)
            s._allocs_by_eval = defaultdict(set)
            s._evals_by_job = defaultdict(set)
            # derived indexes go through the store's builders — the same
            # row constructors the apply path uses (_SNAPSHOT_DERIVED)
            for e in data["evals"]:
                s._index_eval_locked(e)
            s._deployments = {d.id: d for d in data["deployments"]}
            s._job_summaries = dict(data["job_summaries"])
            s.scheduler_config = data["scheduler_config"]
            from nomad_tpu.structs.namespace import Namespace
            s._namespaces = {}
            for name, ns in (data.get("namespaces") or {}).items():
                if isinstance(ns, dict):   # pre-dataclass snapshots
                    ns = Namespace(name=ns.get("name", name),
                                   description=ns.get("description", ""))
                s._namespaces[name] = ns
            if "default" not in s._namespaces:
                s._namespaces["default"] = Namespace(name="default")
            s._quota_specs = dict(data.get("quota_specs", {}))
            # Rebuild usage rows with the same literal keys the store's
            # accounting uses (the outer namespace key stays the loaded
            # object, which pickle shared with the job/alloc namespace
            # strings), so a restored FSM re-snapshots to the same bytes
            # as its peers — the byte-identity gate depends on pickle's
            # string-memoization layout, not just on equal state.
            s._quota_usage = {
                k: {"cpu": v.get("cpu", 0),
                    "memory_mb": v.get("memory_mb", 0),
                    "devices": v.get("devices", 0),
                    "allocs": v.get("allocs", 0)}
                for k, v in data.get("quota_usage", {}).items()}
            s._acl_policies = dict(data.get("acl_policies", {}))
            s._acl_tokens = {}
            s._acl_by_secret = {}
            for t in data.get("acl_tokens", []):
                s._acl_tokens[t.accessor_id] = t
                s._index_acl_token_locked(t)
            s._csi_volumes = dict(data.get("csi_volumes", {}))
            s._csi_plugins = dict(data.get("csi_plugins", {}))
            s._scaling_events = {k: list(v) for k, v in
                                 data.get("scaling_events", {}).items()}
            s._services = {}
            s._services_by_alloc = defaultdict(set)
            for sr in data.get("services", []):
                s._services[sr.id] = sr
                s._index_service_locked(sr)
            s.matrix = ClusterMatrix()
            s.matrix.lock = s._lock
            for n in data["nodes"]:
                s.matrix.upsert_node(n)
            s._live_names = {}
            for a in data["allocs"]:
                s._allocs[a.id] = a
                s._index_alloc_locked(a)
                s.matrix.upsert_alloc(a)
            if "quota_usage" not in data:
                # pre-quota snapshot: derive usage from the live allocs
                from nomad_tpu.structs.namespace import alloc_quota_usage
                for a in data["allocs"]:
                    if not a.terminal_status():
                        s._quota_usage_add(
                            a.namespace, alloc_quota_usage(a), +1)
            s._applied_plan_ids = list(data.get("applied_plan_ids", []))
            s._reindex_applied_plan_ids_locked()
            s.latest_index = data["latest_index"]
            s._snapshot_cache = None
            s._index_cv.notify_all()
        for name, blob_extra in data.get("extra", {}).items():
            fn = getattr(self, "restore_extra", {}).get(name)
            if fn is not None:
                fn(blob_extra)
