"""Raft consensus node (reference: vendored hashicorp/raft as wired in
nomad/server.go:107-111 — elections, log replication, commit, snapshot
install, log compaction).

A compact, threaded Raft: follower/candidate/leader states with randomized
election timeouts, AppendEntries consistency checks, majority commit, an
apply loop feeding the NomadFSM, and InstallSnapshot for followers that
fell behind a compaction.  Designed for in-process clusters over
InMemTransport (the reference's raftInmem test mode) — the production
transport boundary is the same `call(dst, method, args)` surface.
"""
from __future__ import annotations

import concurrent.futures
import logging
import pickle
import queue
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from nomad_tpu import chaos
from nomad_tpu.raft.log import LogEntry, LogStore
from nomad_tpu.raft.meta import DurableMeta, MetaPersistError
from nomad_tpu.raft.snapshot import FileSnapshotStore
from nomad_tpu.raft.transport import InMemTransport, Unreachable

log = logging.getLogger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeaderError(Exception):
    def __init__(self, leader: Optional[str] = None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class _ReadBatch:
    """One leadership-confirmation round shared by every reader that
    joined before its probes went out (reference raft ReadOnlyQueue
    batching): the first reader runs the heartbeat quorum round,
    concurrent readers wait on `event`.  Each reader captures its OWN
    commit index at arrival — the shared round only proves leadership,
    and it proves it for all of them because every probe ack happens
    after the last joiner's capture."""

    __slots__ = ("ok", "event")

    def __init__(self):
        self.ok = False             # quorum confirmed leadership at our term
        self.event = threading.Event()


class RaftConfig:
    def __init__(self,
                 heartbeat_interval: float = 0.05,
                 election_timeout: float = 0.2,
                 snapshot_threshold: int = 2048,
                 max_append_entries: int = 128,
                 lease_clock_skew: float = 0.25):
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.snapshot_threshold = snapshot_threshold
        self.max_append_entries = max_append_entries
        # leader-lease safety margin: a lease anchored at a quorum ack
        # round lasts election_timeout * (1 - skew).  Stickiness means a
        # new leader needs a full election_timeout of quorum silence
        # first, so with any skew > 0 a deposed leader's lease expires
        # strictly before a successor can win — even with clocks drifting
        # by up to `lease_clock_skew` of the timeout (reference
        # consul/nomad LeaderLeaseTimeout < ElectionTimeout).
        self.lease_clock_skew = lease_clock_skew


class RaftNode:
    def __init__(self, name: str, peers: List[str],
                 transport: InMemTransport, fsm,
                 config: Optional[RaftConfig] = None,
                 log_store: Optional[LogStore] = None,
                 snapshots: Optional[FileSnapshotStore] = None,
                 meta: Optional[DurableMeta] = None,
                 on_leader: Optional[Callable[[], None]] = None,
                 on_follower: Optional[Callable[[], None]] = None):
        self.name = name
        self.peers = [p for p in peers if p != name]
        self.transport = transport
        self.fsm = fsm
        self.config = config or RaftConfig()
        self.log = log_store or LogStore()
        self.snapshots = snapshots
        self.meta = meta
        self.on_leader = on_leader
        self.on_follower = on_follower

        self._lock = threading.RLock()
        self.state = FOLLOWER
        # term + vote come back from stable storage (Raft Figure 2): a
        # restarted node that voted this term must still remember it
        self.term = meta.term if meta is not None else 0
        self.voted_for: Optional[str] = \
            meta.voted_for if meta is not None else None
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self._last_snapshot_index = 0
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._futures: Dict[int, concurrent.futures.Future] = {}
        self._last_contact = time.monotonic()
        # leader lease (read path): _ack_round_start[peer] is the send
        # time of the last append round that peer successfully acked; the
        # lease anchors at the majority-th newest of those (self counts as
        # "now") and extends election_timeout * (1 - lease_clock_skew)
        self._ack_round_start: Dict[str, float] = {}
        self._lease_until = 0.0
        self._read_batch: Optional[_ReadBatch] = None
        # one confirmation round in flight at a time: while it runs, the
        # next batch stays open and accumulates joiners (their captured
        # indexes all precede that batch's probes)
        self._round_lock = threading.Lock()
        self.read_rounds = 0        # confirmation rounds run (telemetry)
        self._stop = threading.Event()
        # commit advancement wakes the ticker (hashicorp/raft's per-peer
        # notify channel): followers learn the new commit index on an
        # immediate round instead of waiting out the heartbeat interval,
        # which is what keeps follower read-index waits short under load
        self._commit_event = threading.Event()
        self._apply_cv = threading.Condition(self._lock)
        self._fsm_lock = threading.Lock()   # serializes fsm.apply/restore
        # leadership transitions execute strictly in order through one
        # dispatcher thread (an unordered establish/revoke pair would leave
        # a follower running leader-only subsystems)
        self._leadership_q: "queue.Queue[str]" = queue.Queue()
        self._threads: List[threading.Thread] = []

        # restart recovery: restore the snapshot (committed state only).
        # The persisted log tail is NOT replayed into the FSM here — those
        # entries may be uncommitted and could be truncated by a new
        # leader; they apply normally once a leader advances commit_index
        # (its post-election no-op commits the whole prefix).
        if self.snapshots is not None:
            latest = self.snapshots.latest()
            if latest is not None:
                idx, term, blob = latest
                self.fsm.restore(blob)
                self.last_applied = idx
                self.commit_index = idx
                self._last_snapshot_index = idx
                self._last_snap_term = term

        transport.register(name, self._handle_rpc)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        for target, nm in ((self._run_ticker, "raft-tick"),
                           (self._run_apply, "raft-apply"),
                           (self._run_leadership, "raft-leadership")):
            t = threading.Thread(target=target,
                                 name=f"{nm}-{self.name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._commit_event.set()      # unblock a ticker mid-wait
        with self._apply_cv:
            self._apply_cv.notify_all()
        self.transport.deregister(self.name)
        for t in self._threads:
            t.join(1.0)
        self.log.close()

    def crash(self) -> None:
        """Hard-kill (power loss) simulation for durability soaks: threads
        stop and the WAL loses its unsynced tail — possibly tearing the
        record being appended (chaos `disk.torn_write`).  The meta and
        snapshot files are left exactly as last durably written; restart
        by constructing a fresh node over the same paths."""
        self._stop.set()
        self._commit_event.set()
        with self._apply_cv:
            self._apply_cv.notify_all()
        self.transport.deregister(self.name)
        for t in self._threads:
            t.join(1.0)
        self.log.simulate_crash()

    # --------------------------------------------------------- stable meta

    def _persist_meta(self) -> bool:
        """Write (term, voted_for) to stable storage; True on success.
        Callers gate durability-critical actions (granting a vote,
        launching a candidacy) on the result."""
        if self.meta is None:
            return True
        try:
            self.meta.persist(self.term, self.voted_for)
            return True
        except MetaPersistError:
            log.warning("raft: %s could not persist term/vote; refusing "
                        "the action that required it", self.name,
                        exc_info=True)
            return False

    # ------------------------------------------------------------- public

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def apply(self, msg_type: str, payload,
              timeout: float = 10.0) -> int:
        """Append + replicate + commit + FSM-apply one entry; returns its
        log index (reference raft.Apply)."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            index = self.log.last_index + 1
            # The local propose path must have the same wire-faithful copy
            # semantics as a forwarded RPC (InMemTransport pickles args and
            # results): the leader's log entry is a private copy, so later
            # caller-side mutation of the proposal can never alias FSM state.
            entry = LogEntry(index, self.term, msg_type,
                             pickle.loads(pickle.dumps(payload)))
            self.log.append(entry)
            self._match_index[self.name] = index
            fut: concurrent.futures.Future = concurrent.futures.Future()
            self._futures[index] = fut
            if not self.peers:        # single-voter cluster commits locally
                self._advance_commit()
        self._replicate_all()
        fut.result(timeout=timeout)
        return index

    def barrier(self, timeout: float = 10.0) -> None:
        """Flush the log and wait for it to apply locally (best-effort).

        On a leader this pushes a no-op through the full append/commit/
        apply path (hashicorp/raft Barrier): when it returns, every entry
        committed before the call has been applied — including prior-term
        entries that only BECOME committed via a new-term write.  The
        plain commit_index wait is not enough for a fresh leader: its
        commit_index can lag entries a deposed leader already replicated
        to a majority, and acting on pre-barrier state (e.g. restoring
        evals) would miss their effects."""
        if self.is_leader:
            try:
                self.apply("Noop", None, timeout=timeout)
                return   # future resolves only after local FSM apply
            except Exception:                       # noqa: BLE001
                pass     # deposed or timed out: fall back to local wait
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.last_applied >= self.commit_index:
                    return
            time.sleep(0.005)

    # ------------------------------------------------------------- reads

    def read_index(self, timeout: float = 5.0,
                   lease_ok: bool = True) -> int:
        """Linearizable read point (Raft §6.4 ReadIndex + leader lease).

        On the leader: return commit_index after proving leadership — via
        a still-valid lease (zero network rounds, `lease_ok=True`) or one
        empty-AppendEntries quorum round shared by every reader that
        arrives while it runs.  `lease_ok=False` (the `?consistent` mode)
        always pays the round.  On a follower: raises NotLeaderError —
        the serving gate forwards to the leader, then waits locally via
        `wait_applied(index)`."""
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if chaos.should("read.lease_expire"):
                self._lease_until = 0.0
            if lease_ok and (not self.peers
                             or time.monotonic() < self._lease_until):
                return self.commit_index
            if not self.peers:
                return self.commit_index   # single voter: trivially leader
            # every reader serves at the commit index as of ITS arrival
            # (etcd's readOnly queue): joining an in-flight batch must not
            # hand back an index captured before a write this caller may
            # already have seen acknowledged
            index = self.commit_index
            batch = self._read_batch
            runs_round = batch is None
            if runs_round:
                batch = self._read_batch = _ReadBatch()
            term = self.term
        if runs_round:
            # the round lock serializes confirmation rounds: while a prior
            # round runs, this batch stays published and keeps collecting
            # joiners, and every probe ack below lands strictly after each
            # joiner captured its index — the ordering that lets one
            # shared round confirm all of them
            locked = self._round_lock.acquire(
                timeout=max(0.0, deadline - time.monotonic()))
            try:
                with self._lock:
                    if self._read_batch is batch:
                        self._read_batch = None   # closed: probes start now
                    live = self.state == LEADER and self.term == term
                if live:
                    self._confirm_leadership(batch, term)
            finally:
                if locked:
                    self._round_lock.release()
                batch.event.set()
        else:
            batch.event.wait(max(0.0, deadline - time.monotonic()))
        if not batch.event.is_set():
            raise TimeoutError("raft: read_index confirmation timed out")
        if not batch.ok:
            with self._lock:
                raise NotLeaderError(self.leader_id)
        return index

    def _confirm_leadership(self, batch: _ReadBatch, term: int) -> None:
        """One empty heartbeat round: a majority acking at `term` proves no
        higher-term leader existed when each batched reader captured its
        index, so serving reads at those indexes is linearizable.
        Successful acks also refresh the lease, so a burst of
        `?consistent` reads leaves the default mode round-free."""
        chaos.maybe_delay("read.index_stall")
        self.read_rounds += 1
        start = time.monotonic()
        acks = 1                                    # self
        for peer in self.peers:
            with self._lock:
                if self.state != LEADER or self.term != term:
                    return                          # deposed mid-round
            try:
                # prev_log_index=0 skips the consistency check — this is a
                # pure leadership probe, not replication — so it must also
                # carry leader_commit=0: a real commit index here would let
                # a follower still holding a divergent uncommitted tail
                # from a deposed leader commit its own conflicting entries
                # past the skipped check.  Commit propagation belongs to
                # replication rounds, which do carry prev_log_index.
                resp = self.transport.call(self.name, peer,
                                           "append_entries", {
                    "term": term, "leader": self.name,
                    "prev_log_index": 0, "prev_log_term": 0,
                    "entries": [], "leader_commit": 0})
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                log.warning("raft: %s read probe to %s failed",
                            self.name, peer, exc_info=True)
                continue
            with self._lock:
                if resp["term"] > self.term:
                    self._step_down(resp["term"])
                    return
                if self.state != LEADER or self.term != term:
                    return
                if resp.get("success"):
                    acks += 1
                    self._ack_round_start[peer] = start
                    self._refresh_lease()
        if acks * 2 > len(self.peers) + 1:
            batch.ok = True

    def _refresh_lease(self) -> None:
        """Re-anchor the leader lease (call under self._lock, as leader).

        The lease is valid while a majority — counting ourselves as of
        "now" — acked an append round that started within
        election_timeout * (1 - lease_clock_skew): stickiness guarantees
        no successor can be elected until election_timeout after the
        quorum last heard from us, so the shortened window can never
        overlap a new leader's writes."""
        need = (len(self.peers) + 1) // 2           # peer acks beyond self
        if need == 0:
            anchor = time.monotonic()
        else:
            starts = sorted((self._ack_round_start.get(p, 0.0)
                             for p in self.peers), reverse=True)
            anchor = starts[need - 1]
        lease = anchor + self.config.election_timeout \
            * (1.0 - self.config.lease_clock_skew)
        if lease > self._lease_until:
            self._lease_until = lease

    def lease_valid(self) -> bool:
        with self._lock:
            return self.state == LEADER and (
                not self.peers or time.monotonic() < self._lease_until)

    def wait_applied(self, index: int, timeout: float = 5.0) -> bool:
        """Block until last_applied >= index — the follower half of
        ReadIndex.  Waits on raft's own applied counter, not the store's
        latest_index: a read index can point at a Noop entry the store
        never sees."""
        deadline = time.monotonic() + timeout
        with self._apply_cv:
            while self.last_applied < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._apply_cv.wait(min(remaining, 0.05))
            return True

    def last_contact_ms(self) -> float:
        """Milliseconds since this node last heard from a leader (0 on the
        leader itself) — the X-Nomad-LastContact header value."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            return max(0.0, (time.monotonic() - self._last_contact) * 1e3)

    # ------------------------------------------------------------- ticker

    def _election_deadline(self) -> float:
        to = self.config.election_timeout
        return self._last_contact + to + random.uniform(0, to)

    def _run_ticker(self) -> None:
        while not self._stop.is_set():
            # backstop: the ticker is the only thread that heartbeats and
            # starts elections — if it dies, this node can never lead or
            # vote itself out of a wedge, so no exception may escape
            try:
                with self._lock:
                    state = self.state
                if state == LEADER:
                    self._replicate_all(heartbeat=True)
                    self._maybe_compact()
                    # sleep a heartbeat, or less if a commit advances
                    # (the next round propagates leader_commit at once)
                    self._commit_event.wait(self.config.heartbeat_interval)
                    self._commit_event.clear()
                else:
                    if time.monotonic() >= self._election_deadline():
                        self._run_election()
                    else:
                        self._stop.wait(self.config.heartbeat_interval / 2)
            except Exception:                       # noqa: BLE001
                log.exception("raft: %s ticker iteration failed", self.name)
                self._stop.wait(self.config.heartbeat_interval)

    # ------------------------------------------------------------- election

    def _run_election(self) -> None:
        # Pre-vote round (the reference's preElectSelf): probe whether a
        # quorum WOULD vote for us before touching our real term.  A node
        # that is merely behind — restarting from its data_dir while the
        # leader streams it a snapshot — must not depose a healthy leader
        # just by timing out: without this, its inflated term leaks back
        # through append responses and forces an election it cannot win,
        # over and over, for as long as catch-up takes.  Pre-votes also
        # hit no disk, so an unwinnable election costs zero fsyncs.
        with self._lock:
            term = self.term + 1
            last_index = self.log.last_index
            last_term = self.log.last_term or self._snapshot_term()
        votes = 1
        for peer in self.peers:
            try:
                resp = self.transport.call(self.name, peer, "request_vote", {
                    "term": term, "candidate": self.name, "prevote": True,
                    "last_log_index": last_index, "last_log_term": last_term})
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                log.warning("raft: %s pre-vote call to %s failed",
                            self.name, peer, exc_info=True)
                continue
            if resp.get("granted"):
                votes += 1
        if votes * 2 <= len(self.peers) + 1:
            with self._lock:
                # a quorum sees a live leader (or a better log); wait a
                # full randomized timeout before probing again
                self._last_contact = time.monotonic()
            return
        with self._lock:
            prev_term, prev_vote = self.term, self.voted_for
            if self.term + 1 != term or self.state == LEADER:
                return   # the world moved while we were pre-voting
            self.state = CANDIDATE
            self.term = term
            self.voted_for = self.name
            # the self-vote must hit stable storage before any peer can
            # count it — otherwise a crash-restart mid-election forgets
            # it and this node may vote for someone else in the same term
            if not self._persist_meta():
                self.state = FOLLOWER
                self.term, self.voted_for = prev_term, prev_vote
                return
            self.leader_id = None
            self._last_contact = time.monotonic()
            last_index = self.log.last_index
            last_term = self.log.last_term or self._snapshot_term()
        votes = 1
        for peer in self.peers:
            try:
                resp = self.transport.call(self.name, peer, "request_vote", {
                    "term": term, "candidate": self.name,
                    "last_log_index": last_index, "last_log_term": last_term})
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                log.warning("raft: %s vote call to %s failed",
                            self.name, peer, exc_info=True)
                continue
            with self._lock:
                if resp["term"] > self.term:
                    self._step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        with self._lock:
            if self.state != CANDIDATE or self.term != term:
                return
            if votes * 2 > len(self.peers) + 1:
                self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.name
        # commit a no-op in the new term so prior-term entries become
        # committable immediately (hashicorp/raft's LogNoop on election)
        nxt = self.log.last_index + 1
        self.log.append(LogEntry(nxt, self.term, "Noop", None))
        for p in self.peers:
            self._next_index[p] = nxt
            self._match_index[p] = 0
        self._match_index[self.name] = self.log.last_index
        # a fresh leadership stint must re-earn its lease: ack times from
        # a previous term could anchor a lease the quorum never granted
        self._ack_round_start.clear()
        self._lease_until = 0.0
        if not self.peers:
            self._advance_commit()
        log.info("raft: %s became leader (term %d)", self.name, self.term)
        self._leadership_q.put("leader")

    def _step_down(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.term:
            # adopting a NEW term resets the vote; an equal-term step-down
            # (e.g. a candidate seeing the elected leader's heartbeat)
            # must keep voted_for — clearing it would let this node vote
            # twice in one term.  Persist is best-effort here: a vote
            # granted later in this term re-persists term+vote atomically
            # before it is released.
            self.term = term
            self.voted_for = None
            self._persist_meta()
        if was_leader:
            # don't advertise ourselves as leader after deposition — a
            # stale self-pointing leader_id would make rpc_leader forward
            # to itself in a loop until the new leader's heartbeat arrives
            self.leader_id = None
        # a deposed (or term-bumped) node must never serve lease reads
        self._lease_until = 0.0
        self._ack_round_start.clear()
        self._last_contact = time.monotonic()
        if was_leader:
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(NotLeaderError(self.leader_id))
            self._futures.clear()
            self._leadership_q.put("follower")

    def _run_leadership(self) -> None:
        """Ordered establish/revoke dispatcher (the reference's leaderLoop
        consuming raft.LeaderCh, nomad/leader.go:66-120)."""
        while not self._stop.is_set():
            try:
                evt = self._leadership_q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                if evt == "leader" and self.on_leader is not None:
                    self.on_leader()
                elif evt == "follower" and self.on_follower is not None:
                    self.on_follower()
            except Exception:                       # noqa: BLE001
                log.exception("leadership transition failed")

    def _snapshot_term(self) -> int:
        return 0

    # ------------------------------------------------------------- replicate

    def _replicate_all(self, heartbeat: bool = False) -> None:
        for peer in self.peers:
            try:
                self._replicate_one(peer)
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                # a peer mid-crash raises out of its own handler (closed
                # WAL, dying transport) straight into this thread over the
                # in-process transport; replication just retries next tick
                log.warning("raft: %s replicate to %s failed",
                            self.name, peer, exc_info=True)
                continue

    def _replicate_one(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.term
            nxt = self._next_index.get(peer, self.log.last_index + 1)
            if nxt < self.log.first_index and self.snapshots is not None:
                self._send_snapshot(peer)
                return
            prev_index = nxt - 1
            prev_term = self.log.term_at(prev_index)
            if prev_index > 0 and prev_term == 0 \
                    and prev_index == self._last_snapshot_index:
                prev_term = self._last_snap_term
            entries = self.log.entries_from(
                nxt, self.config.max_append_entries)
            commit = self.commit_index
        round_start = time.monotonic()
        resp = self.transport.call(self.name, peer, "append_entries", {
            "term": term, "leader": self.name,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": [(e.index, e.term, e.msg_type, e.payload)
                        for e in entries],
            "leader_commit": commit})
        with self._lock:
            if resp["term"] > self.term:
                self._step_down(resp["term"])
                return
            if self.state != LEADER or self.term != term:
                return
            if resp.get("success"):
                if entries:
                    self._match_index[peer] = entries[-1].index
                    self._next_index[peer] = entries[-1].index + 1
                self._advance_commit()
                # every successful append/heartbeat ack extends the
                # leader lease from the time the round was SENT (the
                # conservative anchor: leadership was proven as of then)
                self._ack_round_start[peer] = round_start
                self._refresh_lease()
            else:
                # consistency check failed: back off
                self._next_index[peer] = max(
                    1, min(nxt - 1, resp.get("last_index", nxt - 1) + 1))

    _last_snap_term = 0

    def _send_snapshot(self, peer: str) -> None:
        idx = self._last_snapshot_index
        latest = self.snapshots.latest() if self.snapshots else None
        if latest is None:
            return
        s_idx, s_term, blob = latest
        resp = self.transport.call(self.name, peer, "install_snapshot", {
            "term": self.term, "leader": self.name,
            "last_index": s_idx, "last_term": s_term, "data": blob})
        with self._lock:
            if resp["term"] > self.term:
                self._step_down(resp["term"])
                return
            if not resp.get("success"):
                return   # follower could not persist it; retry next round
            self._next_index[peer] = s_idx + 1
            self._match_index[peer] = s_idx

    def _advance_commit(self) -> None:
        """Majority match ⇒ commit (current-term entries only)."""
        matches = sorted(self._match_index.get(p, 0)
                         for p in self.peers + [self.name])
        majority = matches[len(matches) // 2]
        if majority > self.commit_index \
                and self.log.term_at(majority) == self.term:
            self.commit_index = majority
            self._apply_cv.notify_all()
            self._commit_event.set()

    # ------------------------------------------------------------- apply

    def _run_apply(self) -> None:
        """One entry at a time: re-check state under the lock every step so
        a concurrently installed snapshot (which moves last_applied
        forward and compacts the log) can never be undone or spun on."""
        while not self._stop.is_set():
            with self._apply_cv:
                while self.last_applied >= self.commit_index \
                        and not self._stop.is_set():
                    self._apply_cv.wait(0.1)
                if self._stop.is_set():
                    return
                i = self.last_applied + 1
                e = self.log.get(i)
                if e is None:
                    if i <= self._last_snapshot_index:
                        # compacted: the snapshot already covers it
                        self.last_applied = i
                        continue
                    # not replicated yet; wait for it
                    self._apply_cv.wait(0.05)
                    continue
            with self._fsm_lock:
                with self._lock:
                    if i <= self.last_applied:   # snapshot raced us
                        continue
                try:
                    self.fsm.apply(e.index, e.msg_type, e.payload)
                    err = None
                except Exception as exc:           # noqa: BLE001
                    log.exception("fsm apply failed at %d", e.index)
                    err = exc
                with self._lock:
                    self.last_applied = max(self.last_applied, i)
                    fut = self._futures.pop(i, None)
                    # wake wait_applied() readers (the cv shares _lock)
                    self._apply_cv.notify_all()
            if fut is not None and not fut.done():
                if err is None:
                    fut.set_result(i)
                else:
                    fut.set_exception(err)

    # ------------------------------------------------------------- compaction

    def _maybe_compact(self) -> None:
        if self.snapshots is None:
            return
        with self._lock:
            if self.last_applied - self._last_snapshot_index \
                    < self.config.snapshot_threshold:
                return
        self.force_snapshot()

    def force_snapshot(self) -> None:
        """Operator snapshot save (command/raft_tools analogue).  Holds the
        FSM lock so the blob is exactly the state at `last_applied` — a
        concurrent apply landing mid-snapshot would make restart replay
        non-idempotent entries (e.g. job version bumps) twice."""
        if self.snapshots is None:
            return
        with self._fsm_lock:
            with self._lock:
                applied = self.last_applied
                term = self.log.term_at(applied) or self._last_snap_term \
                    or self.term
            blob = self.fsm.snapshot()
        with self._lock:
            try:
                self.snapshots.save(applied, term, blob)
            except Exception:                       # noqa: BLE001
                # incl. injected snapshot.partial_write: the save did NOT
                # land durably, so compacting the log here would orphan
                # the only copy of those entries; keep the log and retry
                # at the next snapshot threshold
                log.warning("raft: %s snapshot save failed; keeping log",
                            self.name, exc_info=True)
                return
            self._last_snapshot_index = applied
            self._last_snap_term = term
            self.log.compact(applied)

    # ------------------------------------------------------------- RPC

    def _handle_rpc(self, method: str, args: dict) -> dict:
        if method == "request_vote":
            return self._on_request_vote(args)
        if method == "append_entries":
            return self._on_append_entries(args)
        if method == "install_snapshot":
            return self._on_install_snapshot(args)
        raise ValueError(method)

    def _on_request_vote(self, a: dict) -> dict:
        with self._lock:
            # leader stickiness (reference requestVote/requestPreVote):
            # while we are hearing from a live leader, refuse — and do NOT
            # adopt the candidate's term.  A partitioned or catching-up
            # node cannot depose a leader the quorum still follows.
            if self.leader_id is not None \
                    and self.leader_id != a["candidate"] \
                    and (time.monotonic() - self._last_contact
                         < self.config.election_timeout):
                return {"term": self.term, "granted": False}
            if a.get("prevote"):
                # would we vote for this candidate in that term?  No state
                # change, no disk: just an electability probe.
                my_last_term = self.log.last_term or self._last_snap_term
                granted = (a["term"] > self.term
                           and (a["last_log_term"] > my_last_term
                                or (a["last_log_term"] == my_last_term
                                    and a["last_log_index"]
                                    >= self.log.last_index)))
                return {"term": self.term, "granted": granted}
            if a["term"] > self.term:
                self._step_down(a["term"])
            granted = False
            if a["term"] == self.term \
                    and self.voted_for in (None, a["candidate"]):
                my_last_term = self.log.last_term or self._last_snap_term
                up_to_date = (
                    a["last_log_term"] > my_last_term
                    or (a["last_log_term"] == my_last_term
                        and a["last_log_index"] >= self.log.last_index))
                if up_to_date:
                    # grant only once the vote is on stable storage: a
                    # granted-then-forgotten vote is the two-leaders bug
                    self.voted_for = a["candidate"]
                    if self._persist_meta():
                        granted = True
                        self._last_contact = time.monotonic()
                    else:
                        self.voted_for = None
            return {"term": self.term, "granted": granted}

    def _on_append_entries(self, a: dict) -> dict:
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False,
                        "last_index": self.log.last_index}
            if a["term"] > self.term or self.state != FOLLOWER:
                self._step_down(a["term"])   # single term-adoption path
            self.leader_id = a["leader"]
            self._last_contact = time.monotonic()
            prev_index = a["prev_log_index"]
            if prev_index > 0:
                local_term = self.log.term_at(prev_index)
                if local_term == 0 and prev_index == self._last_snapshot_index:
                    local_term = self._last_snap_term
                if local_term != a["prev_log_term"] \
                        and prev_index > self._last_snapshot_index:
                    return {"term": self.term, "success": False,
                            "last_index": min(self.log.last_index,
                                              prev_index - 1)}
            # collect the fresh suffix, then append with ONE group-commit
            # durability wait (raft requires entries durable before this
            # response ACKs them — the leader counts us toward commit)
            fresh: List[LogEntry] = []
            for (idx, term, msg_type, payload) in a["entries"]:
                if not fresh:
                    existing = self.log.get(idx)
                    if existing is not None and existing.term == term:
                        continue
                fresh.append(LogEntry(idx, term, msg_type, payload))
            self.log.append_batch(fresh)
            if a["leader_commit"] > self.commit_index:
                self.commit_index = min(a["leader_commit"],
                                        self.log.last_index)
                self._apply_cv.notify_all()
            return {"term": self.term, "success": True,
                    "last_index": self.log.last_index}

    def _on_install_snapshot(self, a: dict) -> dict:
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False}
            if a["term"] > self.term or self.state != FOLLOWER:
                self._step_down(a["term"])   # single term-adoption path
            self.leader_id = a["leader"]
            self._last_contact = time.monotonic()
            # Persist BEFORE accepting.  The snapshot stands in for log
            # entries the leader has already compacted away: if we restore
            # it in memory without a durable copy, later appends land past
            # a hole that exists only on disk, and the next restart replays
            # around the hole — committed state silently vanishes.  Reject
            # instead; the leader keeps us behind and retries the install.
            if self.snapshots is not None:
                try:
                    self.snapshots.save(a["last_index"], a["last_term"],
                                        a["data"])
                except Exception:                   # noqa: BLE001
                    log.warning("raft: %s could not persist installed "
                                "snapshot; rejecting (leader retries)",
                                self.name, exc_info=True)
                    return {"term": self.term, "success": False}
        # fsm_lock outer, _lock inner (same nesting as force_snapshot):
        # last_applied must move in the same critical section as the
        # restore or the apply loop could re-apply a pre-snapshot entry
        # onto the restored state
        with self._fsm_lock:
            with self._lock:
                if a["last_index"] <= self._last_snapshot_index:
                    # duplicate/stale install: never regress the FSM
                    return {"term": self.term, "success": True}
            self.fsm.restore(a["data"])
            with self._lock:
                self._last_snapshot_index = a["last_index"]
                self._last_snap_term = a["last_term"]
                self.log.compact(a["last_index"])
                self.last_applied = max(self.last_applied, a["last_index"])
                self.commit_index = max(self.commit_index, a["last_index"])
                return {"term": self.term, "success": True}
