"""Raft consensus node (reference: vendored hashicorp/raft as wired in
nomad/server.go:107-111 — elections, log replication, commit, snapshot
install, log compaction).

A compact, threaded Raft: follower/candidate/leader states with randomized
election timeouts, AppendEntries consistency checks, majority commit, an
apply loop feeding the NomadFSM, and a streamed, resumable, CRC-framed
InstallSnapshot (dissertation §7 offset/done framing) for followers that
fell behind a compaction — chunk transfers run on their own threads, off
the replication tick, and resume from the follower's acked offset across
drops, restarts and leader changes.  Designed for in-process clusters over
InMemTransport (the reference's raftInmem test mode) — the production
transport boundary is the same `call(dst, method, args)` surface.

Dynamic membership (Raft §4.1, single-server changes): the cluster
configuration — voters plus catch-up non-voters — is itself replicated
as `RaftConfiguration` log entries carried in the WAL and snapshots.
Each entry holds the complete resulting configuration, takes effect on
APPEND (not commit), and only one change may be in flight at a time, so
quorum arithmetic is always computed against the latest appended
configuration and a half-replicated AddVoter already raises the commit
bar.  `add_server`/`remove_server` are the leader-side API; a blank
server boots with `join=True` (empty configuration, never campaigns)
and learns the membership from the entries or snapshot the leader
streams it.  Leadership transfer (`transfer_leadership` → TimeoutNow,
§3.10) fences new proposals, brings the target current, and tells it to
campaign immediately — transfer votes bypass pre-vote and leader
stickiness so the handoff completes in milliseconds.
"""
from __future__ import annotations

import concurrent.futures
import logging
import os
import pickle
import queue
import random
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu import chaos, knobs, tracing
from nomad_tpu.analysis import race
from nomad_tpu.raft.integrity import IntegrityTracker
from nomad_tpu.raft.log import LogEntry, LogStore
from nomad_tpu.raft.meta import DurableMeta, MetaPersistError
from nomad_tpu.raft.snapshot import ChunkSink, FileSnapshotStore
from nomad_tpu.raft.transport import InMemTransport, Unreachable
from nomad_tpu.state import digest as state_digest
from nomad_tpu.telemetry import global_metrics
from nomad_tpu.utils import requires_lock

log = logging.getLogger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

# InstallSnapshot stream frame size (Raft dissertation §7 offset/done
# framing); NOMAD_TPU_SNAP_CHUNK overrides
SNAP_CHUNK_DEFAULT = 256 * 1024

# frames of snapshot blob a sender buffers off disk per peer stream
# (NOMAD_TPU_SNAP_WINDOW overrides): peak sender memory per stream is
# window * chunk, independent of snapshot size
SNAP_WINDOW_DEFAULT = 8

# log entry type carrying a full cluster configuration (Raft §4.1);
# dispatched as a no-op by the FSM — the raft layer consumes it on append
CONFIGURATION_MSG = "RaftConfiguration"

# log entry type carrying an integrity checkpoint (Paxos-Made-Live
# log-stamped state checksums): a no-op for the FSM — the apply loop
# computes the per-table digest when the entry applies, so every
# replica stamps the SAME log position
STATE_CHECKPOINT_MSG = "StateCheckpointRequest"

# entry types the fsm.apply_skip chaos point never skips: skipping a
# no-op cannot create state divergence, and skipping the checkpoint
# itself would blind the very detector the drill is exercising
_APPLY_SKIP_EXEMPT = frozenset({
    "Noop", STATE_CHECKPOINT_MSG, CONFIGURATION_MSG})


class NotLeaderError(Exception):
    def __init__(self, leader: Optional[str] = None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class ConfigurationInFlightError(Exception):
    """A membership change is already appended but not yet committed.
    Raft §4.1 allows exactly one configuration change in flight at a
    time; retry once the pending entry commits."""


class _ReadBatch:
    """One leadership-confirmation round shared by every reader that
    joined before its probes went out (reference raft ReadOnlyQueue
    batching): the first reader runs the heartbeat quorum round,
    concurrent readers wait on `event`.  Each reader captures its OWN
    commit index at arrival — the shared round only proves leadership,
    and it proves it for all of them because every probe ack happens
    after the last joiner's capture."""

    __slots__ = ("ok", "event")

    def __init__(self):
        self.ok = False             # quorum confirmed leadership at our term
        self.event = threading.Event()


class RaftConfig:
    def __init__(self,
                 heartbeat_interval: float = 0.05,
                 election_timeout: float = 0.2,
                 snapshot_threshold: int = 2048,
                 max_append_entries: int = 128,
                 lease_clock_skew: float = 0.25):
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.snapshot_threshold = snapshot_threshold
        self.max_append_entries = max_append_entries
        # leader-lease safety margin: a lease anchored at a quorum ack
        # round lasts election_timeout * (1 - skew).  Stickiness means a
        # new leader needs a full election_timeout of quorum silence
        # first, so with any skew > 0 a deposed leader's lease expires
        # strictly before a successor can win — even with clocks drifting
        # by up to `lease_clock_skew` of the timeout (reference
        # consul/nomad LeaderLeaseTimeout < ElectionTimeout).
        self.lease_clock_skew = lease_clock_skew


class RaftNode:
    # membership configuration tables: every access happens under
    # `self._lock` (lexical `with`, or @requires_lock helpers whose
    # callers hold it); `_apply_cv` is a Condition over the same RLock
    _LOCK_NAME = "_lock"
    _LOCK_ALIASES = ("_apply_cv",)
    _LOCK_PROTECTED = frozenset({"_voters", "_nonvoters"})
    _RACE_TRACED = {"_voters": "_lock"}
    # wait-graph (nomad_tpu.analysis)
    _LOCK_BLOCKING_OK = {
        "_lock": "raft persist-before-respond: term/vote/log entries "
                 "must hit disk under the state lock before any RPC "
                 "reply or role transition (election/RPC timeouts "
                 "bound the stall)",
    }

    def __init__(self, name: str, peers: List[str],
                 transport: InMemTransport, fsm,
                 config: Optional[RaftConfig] = None,
                 log_store: Optional[LogStore] = None,
                 snapshots: Optional[FileSnapshotStore] = None,
                 meta: Optional[DurableMeta] = None,
                 on_leader: Optional[Callable[[], None]] = None,
                 on_follower: Optional[Callable[[], None]] = None,
                 join: bool = False):
        self.name = name
        # Cluster configuration (Raft §4.1): `_voters` take part in
        # elections/quorum/leases; `_nonvoters` only receive replication
        # while they catch up.  A joining server starts with an EMPTY
        # configuration — it never campaigns and learns the membership
        # from the leader's log/snapshot.  `peers` stays the replication
        # target list (everyone but us) for compatibility.
        self._initial_voters = [] if join else sorted(set(peers) | {name})
        self._voters: List[str] = list(self._initial_voters)
        self._nonvoters: List[str] = []
        self._config_index = 0
        self._snap_config: Optional[dict] = None
        self.peers = [p for p in self._voters if p != name]
        self.transport = transport
        self.fsm = fsm
        self.config = config or RaftConfig()
        self.log = log_store or LogStore()
        self.snapshots = snapshots
        self.meta = meta
        self.on_leader = on_leader
        self.on_follower = on_follower

        self._lock = threading.RLock()
        self.state = FOLLOWER
        # term + vote come back from stable storage (Raft Figure 2): a
        # restarted node that voted this term must still remember it
        self.term = meta.term if meta is not None else 0
        self.voted_for: Optional[str] = \
            meta.voted_for if meta is not None else None
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self._last_snapshot_index = 0
        self._last_snap_term = 0
        # outbound snapshot streams (leader): peer -> worker thread, so
        # the chunk loop runs OFF the replication tick and heartbeats to
        # healthy peers never queue behind a catch-up transfer; plus a
        # bounded-backoff table for peers whose installs keep failing
        self._snap_streams: Dict[str, threading.Thread] = {}
        self._snap_backoff: Dict[str, Tuple[int, float]] = {}
        # inbound chunk stream (follower): at most one partial sink at a
        # time, keyed by snapshot identity so a new leader resuming the
        # SAME snapshot continues where the dead one stopped
        self._snap_rx: Optional[ChunkSink] = None
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._futures: Dict[int, concurrent.futures.Future] = {}
        # tracing side table (guarded by _lock): log index -> sampled
        # trace context, noted at propose time on the proposing node so
        # the apply thread can emit the fsm-apply span at observe time.
        # Context never rides in log payloads (FSM byte-identity).
        self._trace_notes: Dict[int, dict] = {}
        self._last_contact = time.monotonic()
        # autopilot health inputs: when the leader last successfully
        # replicated to each peer (append ack or snapshot install)
        self._peer_contact: Dict[str, float] = {}
        # leadership transfer: while set, apply() refuses new proposals
        # and points callers at the target (it will be leader in ms)
        self._transfer_target: Optional[str] = None
        # leader lease (read path): _ack_round_start[peer] is the send
        # time of the last append round that peer successfully acked; the
        # lease anchors at the majority-th newest of those (self counts as
        # "now") and extends election_timeout * (1 - lease_clock_skew)
        self._ack_round_start: Dict[str, float] = {}
        self._lease_until = 0.0
        self._read_batch: Optional[_ReadBatch] = None
        # one confirmation round in flight at a time: while it runs, the
        # next batch stays open and accumulates joiners (their captured
        # indexes all precede that batch's probes)
        self._round_lock = threading.Lock()
        self.read_rounds = 0        # confirmation rounds run (telemetry)
        self._stop = threading.Event()
        # commit advancement wakes the ticker (hashicorp/raft's per-peer
        # notify channel): followers learn the new commit index on an
        # immediate round instead of waiting out the heartbeat interval,
        # which is what keeps follower read-index waits short under load
        self._commit_event = threading.Event()
        self._apply_cv = threading.Condition(self._lock)
        self._fsm_lock = threading.Lock()   # serializes fsm.apply/restore
        # leadership transitions execute strictly in order through one
        # dispatcher thread (an unordered establish/revoke pair would leave
        # a follower running leader-only subsystems)
        self._leadership_q: "queue.Queue[str]" = queue.Queue()
        self._threads: List[threading.Thread] = []

        # replica-integrity plane: per-table digest cache fed by FSM
        # apply hooks, checkpoint vote state (leader), quarantine flag
        self.integrity = IntegrityTracker(self)
        if hasattr(fsm, "dirty_hook"):
            fsm.dirty_hook = self.integrity.note_dirty

        # restart recovery: restore the snapshot (committed state only).
        # The persisted log tail is NOT replayed into the FSM here — those
        # entries may be uncommitted and could be truncated by a new
        # leader; they apply normally once a leader advances commit_index
        # (its post-election no-op commits the whole prefix).
        if self.snapshots is not None:
            rec = self.snapshots.latest_full()
            if rec is not None:
                self.fsm.restore(rec["data"])
                self.last_applied = rec["index"]
                self.commit_index = rec["index"]
                self._last_snapshot_index = rec["index"]
                self._last_snap_term = rec["term"]
                self._snap_config = rec.get("config")

        # entries already in the WAL at boot are recovery replay, not
        # live traffic: the divergence chaos points skip them (an armed
        # fsm.apply_skip firing inside replay would corrupt whichever
        # early entry happens to re-apply first, and two churn restarts
        # replaying the same prefix could then manufacture a corrupt
        # MAJORITY that outvotes the one still-healthy replica)
        self._boot_log_end = self.log.last_index

        # the configuration is part of replicated state: recover the
        # latest one from snapshot / log tail / durable meta — an
        # uncommitted config entry in the WAL is still effective (§4.1,
        # effective on append survives restart)
        with self._lock:
            self._recompute_config(include_meta=True)

        transport.register(name, self._handle_rpc)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        for target, nm in ((self._run_ticker, "raft-tick"),
                           (self._run_apply, "raft-apply"),
                           (self._run_leadership, "raft-leadership")):
            t = threading.Thread(target=target,
                                 name=f"{nm}-{self.name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._commit_event.set()      # unblock a ticker mid-wait
        with self._apply_cv:
            self._apply_cv.notify_all()
        self.transport.deregister(self.name)
        for t in self._threads:
            t.join(1.0)
        self.log.close()

    def crash(self) -> None:
        """Hard-kill (power loss) simulation for durability soaks: threads
        stop and the WAL loses its unsynced tail — possibly tearing the
        record being appended (chaos `disk.torn_write`).  The meta and
        snapshot files are left exactly as last durably written; restart
        by constructing a fresh node over the same paths."""
        self._stop.set()
        self._commit_event.set()
        with self._apply_cv:
            self._apply_cv.notify_all()
        self.transport.deregister(self.name)
        for t in self._threads:
            t.join(1.0)
        self.log.simulate_crash()

    # --------------------------------------------------------- stable meta

    def _persist_meta(self) -> bool:
        """Write (term, voted_for) to stable storage; True on success.
        Callers gate durability-critical actions (granting a vote,
        launching a candidacy) on the result."""
        if self.meta is None:
            return True
        try:
            self.meta.persist(self.term, self.voted_for)
            return True
        except MetaPersistError:
            log.warning("raft: %s could not persist term/vote; refusing "
                        "the action that required it", self.name,
                        exc_info=True)
            return False

    # ----------------------------------------------------- configuration

    @requires_lock("_lock")
    def _quorum(self) -> int:
        """Votes/acks needed for a majority of the CURRENT voter set."""
        return len(self._voters) // 2 + 1 if self._voters else 1

    @requires_lock("_lock")
    def _sole_voter(self) -> bool:
        """True when we are the only voter (non-voters may still exist):
        commit, leases and reads need no network round."""
        return self._voters == [self.name]

    @requires_lock("_lock")
    def _set_config(self, voters, nonvoters, index: int) -> None:
        """Adopt a configuration (effective on append).  Recomputes the
        replication target list and prunes per-peer state for servers
        that left; best-effort mirrors the config into durable meta as a
        recovery belt alongside WAL + snapshot carriage."""
        race.write("RaftNode._voters", self)
        self._voters = sorted(set(voters))
        self._nonvoters = sorted(set(nonvoters) - set(voters))
        self._config_index = index
        self.peers = sorted((set(self._voters) | set(self._nonvoters))
                            - {self.name})
        live = set(self.peers)
        for table in (self._next_index, self._match_index,
                      self._ack_round_start, self._peer_contact):
            for k in list(table):
                if k != self.name and k not in live:
                    table.pop(k, None)
        if self.state == LEADER:
            nxt = self.log.last_index + 1
            for p in self.peers:
                self._next_index.setdefault(p, nxt)
                self._match_index.setdefault(p, 0)
        if self.meta is not None:
            try:
                self.meta.persist_config(
                    {"voters": list(self._voters),
                     "nonvoters": list(self._nonvoters), "index": index})
            except MetaPersistError:
                # WAL + snapshot still carry the config; meta is a
                # recovery convenience, not the durability anchor
                log.warning("raft: %s could not mirror configuration to "
                            "meta", self.name, exc_info=True)

    @requires_lock("_lock")
    def _recompute_config(self, include_meta: bool = False) -> None:
        """Rebuild the effective configuration from what storage actually
        holds: the newest of (initial static config, snapshot config,
        config entries still in the log[, durable-meta mirror]).  Used at
        boot and after a follower truncates a conflicting suffix that may
        have carried the configuration it was running."""
        best = {"voters": list(self._initial_voters), "nonvoters": [],
                "index": 0}
        for cand in ((self._snap_config,
                      self.meta.config if include_meta
                      and self.meta is not None else None)):
            if cand and cand.get("index", 0) >= best["index"]:
                best = cand
        for e in self.log.entries_of_type(CONFIGURATION_MSG):
            if e.index >= best["index"]:
                best = {"voters": list(e.payload["voters"]),
                        "nonvoters": list(e.payload["nonvoters"]),
                        "index": e.index}
        self._set_config(best["voters"], best.get("nonvoters", []),
                         best.get("index", 0))

    @requires_lock("_lock")
    def _config_at(self, index: int) -> Optional[dict]:
        """The configuration as of log `index` (for snapshot carriage):
        the newest config entry at or below it, else the snapshot's own
        config, else the initial static config."""
        best = self._snap_config
        for e in self.log.entries_of_type(CONFIGURATION_MSG):
            if e.index <= index and (best is None
                                     or e.index >= best.get("index", 0)):
                best = {"voters": list(e.payload["voters"]),
                        "nonvoters": list(e.payload["nonvoters"]),
                        "index": e.index}
        if best is None and self._initial_voters:
            best = {"voters": list(self._initial_voters), "nonvoters": [],
                    "index": 0}
        return best

    def configuration(self) -> dict:
        """Operator view of the replicated membership (the
        `/v1/operator/raft/configuration` payload)."""
        with self._lock:
            race.read("RaftNode._voters", self)
            return {"voters": list(self._voters),
                    "nonvoters": list(self._nonvoters),
                    "index": self._config_index,
                    "leader": self.leader_id,
                    "term": self.term}

    def add_server(self, server: str, voter: bool = False,
                   timeout: float = 10.0) -> int:
        """AddVoter / AddNonvoter (leader only).  New servers normally
        join as non-voters and are promoted (`voter=True` on an existing
        non-voter) once the autopilot health gate passes; adding straight
        to voter is allowed but raises the quorum bar immediately."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            voters, nonvoters = set(self._voters), set(self._nonvoters)
            if voter:
                if server in voters:
                    return self._config_index
                voters.add(server)
                nonvoters.discard(server)
            else:
                if server in voters or server in nonvoters:
                    return self._config_index
                nonvoters.add(server)
        return self._append_config(sorted(voters), sorted(nonvoters),
                                   timeout)

    def remove_server(self, server: str, timeout: float = 10.0) -> int:
        """RemoveServer (leader only).  Removing the leader itself is
        transfer-then-demote: hand leadership off first, then let the
        caller retry against the successor (which performs the actual
        removal) — the deposed leader never has to commit its own
        removal under a quorum it no longer anchors.  If no transfer
        target exists the leader commits its own removal and steps down
        once the entry applies (Raft §4.2.2)."""
        with self._lock:
            self_removal = self.state == LEADER and server == self.name
        if self_removal and self.transfer_leadership():
            with self._lock:
                raise NotLeaderError(self.leader_id)
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            voters, nonvoters = set(self._voters), set(self._nonvoters)
            if server not in voters and server not in nonvoters:
                return self._config_index
            if voters == {server}:
                raise ValueError("cannot remove the last voter")
            voters.discard(server)
            nonvoters.discard(server)
        return self._append_config(sorted(voters), sorted(nonvoters),
                                   timeout)

    def _append_config(self, voters: List[str], nonvoters: List[str],
                       timeout: float) -> int:
        """Append one configuration entry and wait for it to commit.
        Enforces the §4.1 one-change-in-flight rule; the new config is
        effective the moment the entry is appended, BEFORE it commits."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if self._transfer_target is not None:
                raise NotLeaderError(self._transfer_target)
            if self._config_index > self.commit_index:
                raise ConfigurationInFlightError(
                    f"configuration change at index {self._config_index} "
                    f"is not yet committed (commit={self.commit_index})")
            if chaos.active is not None \
                    and chaos.should("raft.config_conflict"):
                raise ConfigurationInFlightError(
                    "chaos: injected configuration conflict")
            index = self.log.last_index + 1
            self.log.append(LogEntry(index, self.term, CONFIGURATION_MSG,
                                     {"voters": list(voters),
                                      "nonvoters": list(nonvoters)}))
            self._set_config(voters, nonvoters, index)
            self._match_index[self.name] = index
            fut: concurrent.futures.Future = concurrent.futures.Future()
            self._futures[index] = fut
            self._advance_commit()     # sole-voter configs commit locally
        self._replicate_all()
        fut.result(timeout=timeout)
        return index

    def server_healthy(self, server: str, lag: int = 16) -> bool:
        """Autopilot promotion gate (leader only): we heard an ack from
        the server within one election timeout AND its log is within
        `lag` entries of ours — the stabilization window the caller
        enforces on top makes a flapping server re-earn both."""
        with self._lock:
            if self.state != LEADER:
                return False
            fresh = (time.monotonic() - self._peer_contact.get(server, 0.0)
                     < self.config.election_timeout)
            caught = self._match_index.get(server, 0) \
                >= self.log.last_index - lag
        if self.integrity.peer_divergent(server):
            # a digest-convicted replica is never promoted, whatever its
            # log position — it re-earns health via verified repair
            return False
        return fresh and caught

    # ----------------------------------------------------------- transfer

    def transfer_leadership(self, target: Optional[str] = None,
                            timeout: Optional[float] = None) -> bool:
        """Graceful handoff (Raft §3.10 / TimeoutNow).  Fences new
        proposals, brings the target fully current, then tells it to
        campaign immediately — its RequestVote carries `transfer: True`,
        bypassing pre-vote and leader stickiness, so the handoff lands in
        milliseconds instead of an election timeout.  Returns True once
        we observe our own deposition (the successor's higher term);
        False re-arms normal proposal service."""
        if timeout is None:
            timeout = self.config.election_timeout * 3
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            candidates = [v for v in self._voters if v != self.name]
            if target is None:
                if not candidates:
                    return False
                target = max(candidates,
                             key=lambda p: self._match_index.get(p, 0))
            elif target not in candidates:
                raise ValueError(f"transfer target {target!r} is not a "
                                 f"voter")
            self._transfer_target = target
            term = self.term
        try:
            while True:
                with self._lock:
                    if self.state != LEADER or self.term != term:
                        return False
                    caught = self._match_index.get(target, 0) \
                        >= self.log.last_index
                if caught:
                    break
                if time.monotonic() >= deadline:
                    return False
                try:
                    self._replicate_one(target)
                except Unreachable:
                    return False     # target gone: resume normal duty
                except Exception:                   # noqa: BLE001
                    log.warning("raft: %s transfer catch-up to %s failed",
                                self.name, target, exc_info=True)
                time.sleep(0.002)
            if chaos.active is not None and chaos.should("transfer.timeout"):
                # injected: the TimeoutNow never reaches the target; the
                # caller falls back to a normal election timeout
                return False
            try:
                resp = self.transport.call(self.name, target, "timeout_now",
                                           {"term": term,
                                            "leader": self.name})
            except Exception:                       # noqa: BLE001
                return False
            if not resp.get("success"):
                return False
            # success manifests as our own deposition: the target's
            # higher-term RequestVote (or its first heartbeat) steps us
            # down; wait out the deadline for it
            while time.monotonic() < deadline:
                with self._lock:
                    if self.state != LEADER or self.term != term:
                        return True
                time.sleep(0.002)
            return False
        finally:
            with self._lock:
                if self._transfer_target == target:
                    self._transfer_target = None

    # ------------------------------------------------------------- public

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def apply(self, msg_type: str, payload,
              timeout: float = 10.0) -> int:
        """Append + replicate + commit + FSM-apply one entry; returns its
        log index (reference raft.Apply)."""
        tracer = tracing.active
        tctx = tracing.current() if tracer is not None else None
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if self._transfer_target is not None:
                # transferring: stop taking proposals so the target can
                # catch up to a FIXED last_index; it will be leader in ms
                raise NotLeaderError(self._transfer_target)
            index = self.log.last_index + 1
            # The local propose path must have the same wire-faithful copy
            # semantics as a forwarded RPC (InMemTransport pickles args and
            # results): the leader's log entry is a private copy, so later
            # caller-side mutation of the proposal can never alias FSM state.
            entry = LogEntry(index, self.term, msg_type,
                             pickle.loads(pickle.dumps(payload)))
            t0 = time.time() if tctx is not None else 0.0
            self.log.append(entry)
            if tctx is not None:
                # propose-time: the WAL append (including its fsync) is
                # a span, and the index->context note lets _run_apply
                # emit the fsm-apply span without touching the payload
                tracer.emit(tctx, "raft.append", t0, time.time(),
                            node=self.name, index=index)
                if len(self._trace_notes) > 1024:
                    self._trace_notes.clear()   # leadership-churn strays
                self._trace_notes[index] = tctx
            self._match_index[self.name] = index
            fut: concurrent.futures.Future = concurrent.futures.Future()
            self._futures[index] = fut
            self._advance_commit()    # sole-voter clusters commit locally
        t1 = time.time() if tctx is not None else 0.0
        self._replicate_all()
        fut.result(timeout=timeout)
        if tctx is not None:
            # replicate + quorum commit + local FSM apply wait
            tracer.emit(tctx, "raft.commit", t1, time.time(),
                        node=self.name, index=index)
        return index

    def proposal_depth(self) -> int:
        """In-flight proposal count (appended, not yet applied) — the
        brownout monitor's overload signal.  A bare len() read: the
        sampled signal tolerates staleness, so no lock is taken."""
        return len(self._futures)

    def barrier(self, timeout: float = 10.0) -> None:
        """Flush the log and wait for it to apply locally (best-effort).

        On a leader this pushes a no-op through the full append/commit/
        apply path (hashicorp/raft Barrier): when it returns, every entry
        committed before the call has been applied — including prior-term
        entries that only BECOME committed via a new-term write.  The
        plain commit_index wait is not enough for a fresh leader: its
        commit_index can lag entries a deposed leader already replicated
        to a majority, and acting on pre-barrier state (e.g. restoring
        evals) would miss their effects."""
        if self.is_leader:
            try:
                self.apply("Noop", None, timeout=timeout)
                return   # future resolves only after local FSM apply
            except Exception:                       # noqa: BLE001
                pass     # deposed or timed out: fall back to local wait
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.last_applied >= self.commit_index:
                    return
            time.sleep(0.005)

    # ------------------------------------------------------------- reads

    def read_index(self, timeout: float = 5.0,
                   lease_ok: bool = True) -> int:
        """Linearizable read point (Raft §6.4 ReadIndex + leader lease).

        On the leader: return commit_index after proving leadership — via
        a still-valid lease (zero network rounds, `lease_ok=True`) or one
        empty-AppendEntries quorum round shared by every reader that
        arrives while it runs.  `lease_ok=False` (the `?consistent` mode)
        always pays the round.  On a follower: raises NotLeaderError —
        the serving gate forwards to the leader, then waits locally via
        `wait_applied(index)`."""
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            if chaos.should("read.lease_expire"):
                self._lease_until = 0.0
            if lease_ok and (self._sole_voter()
                             or time.monotonic() < self._lease_until):
                return self.commit_index
            if self._sole_voter():
                return self.commit_index   # single voter: trivially leader
            # every reader serves at the commit index as of ITS arrival
            # (etcd's readOnly queue): joining an in-flight batch must not
            # hand back an index captured before a write this caller may
            # already have seen acknowledged
            index = self.commit_index
            batch = self._read_batch
            runs_round = batch is None
            if runs_round:
                batch = self._read_batch = _ReadBatch()
            term = self.term
        if runs_round:
            # the round lock serializes confirmation rounds: while a prior
            # round runs, this batch stays published and keeps collecting
            # joiners, and every probe ack below lands strictly after each
            # joiner captured its index — the ordering that lets one
            # shared round confirm all of them
            locked = self._round_lock.acquire(
                timeout=max(0.0, deadline - time.monotonic()))
            try:
                with self._lock:
                    if self._read_batch is batch:
                        self._read_batch = None   # closed: probes start now
                    live = self.state == LEADER and self.term == term
                if live:
                    self._confirm_leadership(batch, term)
            finally:
                if locked:
                    self._round_lock.release()
                batch.event.set()
        else:
            batch.event.wait(max(0.0, deadline - time.monotonic()))
        if not batch.event.is_set():
            raise TimeoutError("raft: read_index confirmation timed out")
        if not batch.ok:
            with self._lock:
                raise NotLeaderError(self.leader_id)
        return index

    def _confirm_leadership(self, batch: _ReadBatch, term: int) -> None:
        """One empty heartbeat round: a majority acking at `term` proves no
        higher-term leader existed when each batched reader captured its
        index, so serving reads at those indexes is linearizable.
        Successful acks also refresh the lease, so a burst of
        `?consistent` reads leaves the default mode round-free."""
        chaos.maybe_delay("read.index_stall")
        self.read_rounds += 1
        start = time.monotonic()
        with self._lock:
            # leadership is proven by VOTERS only: a non-voter's ack says
            # nothing about who the electorate follows
            probe_peers = [v for v in self._voters if v != self.name]
            quorum = self._quorum()
            acks = 1 if self.name in self._voters else 0
        for peer in probe_peers:
            with self._lock:
                if self.state != LEADER or self.term != term:
                    return                          # deposed mid-round
            try:
                # prev_log_index=0 skips the consistency check — this is a
                # pure leadership probe, not replication — so it must also
                # carry leader_commit=0: a real commit index here would let
                # a follower still holding a divergent uncommitted tail
                # from a deposed leader commit its own conflicting entries
                # past the skipped check.  Commit propagation belongs to
                # replication rounds, which do carry prev_log_index.
                resp = self.transport.call(self.name, peer,
                                           "append_entries", {
                    "term": term, "leader": self.name,
                    "prev_log_index": 0, "prev_log_term": 0,
                    "entries": [], "leader_commit": 0})
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                log.warning("raft: %s read probe to %s failed",
                            self.name, peer, exc_info=True)
                continue
            with self._lock:
                if resp["term"] > self.term:
                    self._step_down(resp["term"])
                    return
                if self.state != LEADER or self.term != term:
                    return
                if resp.get("success"):
                    acks += 1
                    self._ack_round_start[peer] = start
                    self._refresh_lease()
        if acks >= quorum:
            batch.ok = True

    @requires_lock("_lock")
    def _refresh_lease(self) -> None:
        """Re-anchor the leader lease (call under self._lock, as leader).

        The lease is valid while a majority — counting ourselves as of
        "now" — acked an append round that started within
        election_timeout * (1 - lease_clock_skew): stickiness guarantees
        no successor can be elected until election_timeout after the
        quorum last heard from us, so the shortened window can never
        overlap a new leader's writes."""
        if self.name not in self._voters:
            return            # a non-voter leader-in-demotion holds no lease
        need = self._quorum() - 1                   # voter acks beyond self
        if need == 0:
            anchor = time.monotonic()
        else:
            starts = sorted((self._ack_round_start.get(v, 0.0)
                             for v in self._voters if v != self.name),
                            reverse=True)
            anchor = starts[need - 1]
        lease = anchor + self.config.election_timeout \
            * (1.0 - self.config.lease_clock_skew)
        if lease > self._lease_until:
            self._lease_until = lease

    def lease_valid(self) -> bool:
        with self._lock:
            return self.state == LEADER and (
                self._sole_voter()
                or time.monotonic() < self._lease_until)

    def wait_applied(self, index: int, timeout: float = 5.0) -> bool:
        """Block until last_applied >= index — the follower half of
        ReadIndex.  Waits on raft's own applied counter, not the store's
        latest_index: a read index can point at a Noop entry the store
        never sees."""
        deadline = time.monotonic() + timeout
        with self._apply_cv:
            while self.last_applied < index:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._apply_cv.wait(min(remaining, 0.05))
            return True

    def last_contact_ms(self) -> float:
        """Milliseconds since this node last heard from a leader (0 on the
        leader itself) — the X-Nomad-LastContact header value."""
        with self._lock:
            if self.state == LEADER:
                return 0.0
            return max(0.0, (time.monotonic() - self._last_contact) * 1e3)

    # ------------------------------------------------------------- ticker

    def _election_deadline(self) -> float:
        to = self.config.election_timeout
        return self._last_contact + to + random.uniform(0, to)

    def _run_ticker(self) -> None:
        while not self._stop.is_set():
            # backstop: the ticker is the only thread that heartbeats and
            # starts elections — if it dies, this node can never lead or
            # vote itself out of a wedge, so no exception may escape
            try:
                with self._lock:
                    state = self.state
                if state == LEADER:
                    self._replicate_all(heartbeat=True)
                    self._maybe_compact()
                    # sleep a heartbeat, or less if a commit advances
                    # (the next round propagates leader_commit at once)
                    self._commit_event.wait(self.config.heartbeat_interval)
                    self._commit_event.clear()
                else:
                    if time.monotonic() >= self._election_deadline():
                        self._run_election()
                    else:
                        self._stop.wait(self.config.heartbeat_interval / 2)
            except Exception:                       # noqa: BLE001
                log.exception("raft: %s ticker iteration failed", self.name)
                self._stop.wait(self.config.heartbeat_interval)

    # ------------------------------------------------------------- election

    def _run_election(self, transfer: bool = False) -> None:
        # Pre-vote round (the reference's preElectSelf): probe whether a
        # quorum WOULD vote for us before touching our real term.  A node
        # that is merely behind — restarting from its data_dir while the
        # leader streams it a snapshot — must not depose a healthy leader
        # just by timing out: without this, its inflated term leaks back
        # through append responses and forces an election it cannot win,
        # over and over, for as long as catch-up takes.  Pre-votes also
        # hit no disk, so an unwinnable election costs zero fsyncs.
        # `transfer=True` (TimeoutNow, §3.10) skips the pre-vote — the
        # outgoing leader explicitly asked us to campaign NOW, and its own
        # liveness is exactly what pre-vote/stickiness would hold against
        # us.
        with self._lock:
            if self.name not in self._voters:
                # non-voters (joining servers, demoted members) never
                # campaign; they wait for a leader to contact them
                self._last_contact = time.monotonic()
                return
            vote_peers = [v for v in self._voters if v != self.name]
            quorum = self._quorum()
            term = self.term + 1
            last_index = self.log.last_index
            last_term = self.log.last_term or self._snapshot_term()
        if not transfer:
            votes = 1
            for peer in vote_peers:
                try:
                    resp = self.transport.call(
                        self.name, peer, "request_vote", {
                            "term": term, "candidate": self.name,
                            "prevote": True, "last_log_index": last_index,
                            "last_log_term": last_term})
                except Unreachable:
                    continue
                except Exception:                   # noqa: BLE001
                    log.warning("raft: %s pre-vote call to %s failed",
                                self.name, peer, exc_info=True)
                    continue
                if resp.get("granted"):
                    votes += 1
            if votes < quorum:
                with self._lock:
                    # a quorum sees a live leader (or a better log); wait a
                    # full randomized timeout before probing again
                    self._last_contact = time.monotonic()
                return
        with self._lock:
            prev_term, prev_vote = self.term, self.voted_for
            if self.term + 1 != term or self.state == LEADER \
                    or self.name not in self._voters:
                return   # the world moved while we were pre-voting
            self.state = CANDIDATE
            self.term = term
            self.voted_for = self.name
            # the self-vote must hit stable storage before any peer can
            # count it — otherwise a crash-restart mid-election forgets
            # it and this node may vote for someone else in the same term
            if not self._persist_meta():
                self.state = FOLLOWER
                self.term, self.voted_for = prev_term, prev_vote
                return
            self.leader_id = None
            self._last_contact = time.monotonic()
            vote_peers = [v for v in self._voters if v != self.name]
            quorum = self._quorum()
            last_index = self.log.last_index
            last_term = self.log.last_term or self._snapshot_term()
        votes = 1
        for peer in vote_peers:
            try:
                resp = self.transport.call(self.name, peer, "request_vote", {
                    "term": term, "candidate": self.name,
                    "transfer": transfer,
                    "last_log_index": last_index, "last_log_term": last_term})
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                log.warning("raft: %s vote call to %s failed",
                            self.name, peer, exc_info=True)
                continue
            with self._lock:
                if resp["term"] > self.term:
                    self._step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        with self._lock:
            if self.state != CANDIDATE or self.term != term:
                return
            if votes >= quorum:
                self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.name
        # commit a no-op in the new term so prior-term entries become
        # committable immediately (hashicorp/raft's LogNoop on election)
        nxt = self.log.last_index + 1
        self.log.append(LogEntry(nxt, self.term, "Noop", None))
        for p in self.peers:
            self._next_index[p] = nxt
            self._match_index[p] = 0
        self._match_index[self.name] = self.log.last_index
        # a fresh leadership stint must re-earn its lease: ack times from
        # a previous term could anchor a lease the quorum never granted
        self._ack_round_start.clear()
        self._lease_until = 0.0
        self._advance_commit()        # sole-voter: the no-op commits now
        log.info("raft: %s became leader (term %d)", self.name, self.term)
        self._leadership_q.put("leader")

    def _step_down(self, term: int) -> None:
        was_leader = self.state == LEADER
        self.state = FOLLOWER
        if term > self.term:
            # adopting a NEW term resets the vote; an equal-term step-down
            # (e.g. a candidate seeing the elected leader's heartbeat)
            # must keep voted_for — clearing it would let this node vote
            # twice in one term.  Persist is best-effort here: a vote
            # granted later in this term re-persists term+vote atomically
            # before it is released.
            self.term = term
            self.voted_for = None
            self._persist_meta()
        if was_leader:
            # don't advertise ourselves as leader after deposition — a
            # stale self-pointing leader_id would make rpc_leader forward
            # to itself in a loop until the new leader's heartbeat arrives
            self.leader_id = None
        # a deposed (or term-bumped) node must never serve lease reads
        self._lease_until = 0.0
        self._ack_round_start.clear()
        self._last_contact = time.monotonic()
        if was_leader:
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(NotLeaderError(self.leader_id))
            self._futures.clear()
            self._leadership_q.put("follower")

    def _run_leadership(self) -> None:
        """Ordered establish/revoke dispatcher (the reference's leaderLoop
        consuming raft.LeaderCh, nomad/leader.go:66-120)."""
        while not self._stop.is_set():
            try:
                evt = self._leadership_q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                if evt == "leader" and self.on_leader is not None:
                    self.on_leader()
                elif evt == "follower" and self.on_follower is not None:
                    self.on_follower()
            except Exception:                       # noqa: BLE001
                log.exception("leadership transition failed")

    def _snapshot_term(self) -> int:
        """Term of the newest installed snapshot: the candidate's
        last-log-term fallback once compaction has emptied the log — a
        fully-compacted node advertising term 0 could never win a
        (pre-)vote against peers comparing it to the snapshot's real
        term."""
        return self._last_snap_term

    # ------------------------------------------------------------- replicate

    def _replicate_all(self, heartbeat: bool = False) -> None:
        for peer in self.peers:
            try:
                self._replicate_one(peer)
            except Unreachable:
                continue
            except Exception:                       # noqa: BLE001
                # a peer mid-crash raises out of its own handler (closed
                # WAL, dying transport) straight into this thread over the
                # in-process transport; replication just retries next tick
                log.warning("raft: %s replicate to %s failed",
                            self.name, peer, exc_info=True)
                continue

    def _replicate_one(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.term
            nxt = self._next_index.get(peer, self.log.last_index + 1)
            if nxt < self.log.first_index and self.snapshots is not None:
                self._spawn_snapshot_stream(peer)
                return
            prev_index = nxt - 1
            prev_term = self.log.term_at(prev_index)
            if prev_index > 0 and prev_term == 0 \
                    and prev_index == self._last_snapshot_index:
                prev_term = self._last_snap_term
            entries = self.log.entries_from(
                nxt, self.config.max_append_entries)
            commit = self.commit_index
        round_start = time.monotonic()
        args = {
            "term": term, "leader": self.name,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": [(e.index, e.term, e.msg_type, e.payload)
                        for e in entries],
            "leader_commit": commit}
        bad_table = self.integrity.peer_divergent(peer)
        if bad_table:
            # convicted peer: the quarantine directive rides every
            # append until the repair stream digest-verifies
            args["integrity_quarantine"] = bad_table
        resp = self.transport.call(self.name, peer, "append_entries", args)
        with self._lock:
            if resp["term"] > self.term:
                self._step_down(resp["term"])
                return
            if self.state != LEADER or self.term != term:
                return
            if resp.get("success"):
                if entries:
                    self._match_index[peer] = entries[-1].index
                    self._next_index[peer] = entries[-1].index + 1
                self._advance_commit()
                # every successful append/heartbeat ack extends the
                # leader lease from the time the round was SENT (the
                # conservative anchor: leadership was proven as of then)
                self._ack_round_start[peer] = round_start
                self._peer_contact[peer] = time.monotonic()
                self._refresh_lease()
                self.integrity.observe_ack(peer, resp.get("integrity"))
            else:
                # consistency check failed: back off
                self._next_index[peer] = max(
                    1, min(nxt - 1, resp.get("last_index", nxt - 1) + 1))
                return
        # checkpoint vote + repair kicks run with no locks held — the
        # evaluation takes only the tracker's leaf lock, and a repair
        # spawn may force a snapshot (fsm lock)
        self._integrity_evaluate()

    def _integrity_evaluate(self) -> None:
        """Leader-side checkpoint vote (no locks held on entry): judge
        the newest checkpoint by majority, quarantine convicted peers
        (the directive rides their next append), kick anti-entropy
        repair streams, and — if WE lost the vote — quarantine our own
        reads and hand leadership off so the successor repairs us as a
        follower."""
        with self._lock:
            if self.state != LEADER:
                return
            race.read("RaftNode._voters", self)
            voters = list(self._voters) or [self.name]
            members = set(self._voters) | set(self._nonvoters) \
                | {self.name}
        actions = self.integrity.evaluate(voters, members=members)
        if actions["self_outlier"]:
            if not self.integrity.quarantined:
                self.integrity.quarantine(
                    "lost integrity majority vote as leader")
                threading.Thread(
                    target=self._integrity_step_aside,
                    name=f"raft-integrity-stepdown-{self.name}",
                    daemon=True).start()
            return
        if not actions["repair"]:
            return
        need_spawn = []
        now = time.monotonic()
        with self._lock:
            if self.state != LEADER:
                return
            for peer in actions["repair"]:
                t = self._snap_streams.get(peer)
                if t is not None and t.is_alive():
                    continue
                _, next_ok = self._snap_backoff.get(peer, (0, 0.0))
                if now < next_ok:
                    continue
                need_spawn.append(peer)
        if not need_spawn:
            return
        # a FRESH snapshot so the repair base (and its expected digest)
        # is at/above the judged checkpoint — the stream then rides the
        # ordinary chunked InstallSnapshot machinery with repair framing
        self.force_snapshot()
        with self._lock:
            if self.state != LEADER:
                return
            for peer in need_spawn:
                self._spawn_snapshot_stream(peer, repair=True)

    def _integrity_step_aside(self) -> None:
        """A leader convicted by its own integrity vote transfers
        leadership away (runs on a helper thread — transfer blocks on
        the target catching up)."""
        try:
            if not self.transfer_leadership():
                log.warning("raft: %s integrity step-aside could not "
                            "transfer leadership", self.name)
        except (NotLeaderError, ValueError):
            pass
        except Exception:                           # noqa: BLE001
            log.warning("raft: %s integrity step-aside failed",
                        self.name, exc_info=True)

    @requires_lock("_lock")
    def _spawn_snapshot_stream(self, peer: str,
                               repair: bool = False) -> None:
        """Kick off (or leave running) the chunked snapshot transfer to a
        lagging peer.  Called from the replication tick under `_lock`;
        only spawns the worker thread, so heartbeats to the remaining
        peers proceed immediately.  `repair=True` streams with integrity
        repair framing (see _send_snapshot)."""
        t = self._snap_streams.get(peer)
        if t is not None and t.is_alive():
            return
        _, next_ok = self._snap_backoff.get(peer, (0, 0.0))
        if time.monotonic() < next_ok:
            return      # bounded backoff after repeated install failures
        t = threading.Thread(target=self._send_snapshot,
                             args=(peer, repair),
                             name=f"raft-snap-{self.name}-{peer}",
                             daemon=True)
        self._snap_streams[peer] = t
        t.start()

    def _note_snap_failure(self, peer: str) -> None:
        """A snapshot stream attempt failed: count it and arm bounded
        exponential backoff so a follower that persistently fails to
        persist is not re-streamed the full blob every tick forever."""
        global_metrics.incr("raft.snapshot.send_fail")
        with self._lock:
            fails, _ = self._snap_backoff.get(peer, (0, 0.0))
            fails = min(fails + 1, 6)
            delay = min(2.0, 0.05 * (2 ** fails))
            self._snap_backoff[peer] = (fails, time.monotonic() + delay)

    def _send_snapshot(self, peer: str, repair: bool = False) -> None:
        """Streamed, resumable InstallSnapshot (dissertation §7).

        Runs on its own thread, off the replication tick.  The blob goes
        out in `NOMAD_TPU_SNAP_CHUNK`-byte frames, each carrying
        {offset, crc32, total, done, last_index, last_term, config};
        every ack returns the follower's next expected offset, which is
        the whole resume protocol — a dropped/duplicated/reordered frame
        re-syncs to the ack, a restarted follower acks 0, and a NEW
        leader streaming the same snapshot picks up at the offset the
        dead leader's stream reached.  The `done` frame adds the
        whole-stream CRC so the follower persists only a verified blob.

        `repair=True` is the anti-entropy channel for a digest-convicted
        peer: every frame carries ``repair: True`` (the follower's
        install bypasses the dup/skip-restore guards and rewinds
        last_applied to the snapshot index — entries above it re-apply
        onto the restored base), and the `done` frame carries the
        combined digest of the streamed blob so the follower can
        digest-verify its restored state before re-admitting itself.
        """
        stream = None
        try:
            chunk = max(1, knobs.get_int(
                "NOMAD_TPU_SNAP_CHUNK", default=SNAP_CHUNK_DEFAULT))
            window = max(1, knobs.get_int(
                "NOMAD_TPU_SNAP_WINDOW", default=SNAP_WINDOW_DEFAULT))
            # windowed read handle: frames come off the sidecar blob
            # file at most `window` chunks at a time, so N concurrent
            # peer streams cost N*window*chunk — not N whole blobs
            stream = self.snapshots.open_stream(window * chunk) \
                if self.snapshots else None
            if stream is None:
                return
            s_idx, s_term = stream.index, stream.term
            total = stream.total
            stream_crc = stream.stream_crc
            snap_config = stream.config
            expected_digest = None
            if repair:
                # expected digest of the streamed state, computed from
                # the SAME blob (one transient full read — repair only)
                rec = self.snapshots.latest_full()
                if rec is None or rec["index"] != s_idx:
                    # another snapshot landed between open and read:
                    # retry next tick with a consistent blob/digest pair
                    self._note_snap_failure(peer)
                    return
                expected_digest = state_digest.combine(
                    state_digest.blob_digests(rec["data"]))
            offset = 0
            stalls = drops = 0
            while True:
                with self._lock:
                    if self.state != LEADER or self._stop.is_set():
                        return
                    term = self.term
                if chaos.active is not None \
                        and chaos.should("snapshot.stream_abort"):
                    # sender dies mid-transfer (leader kill / stream
                    # teardown): the next replication tick restarts the
                    # stream, which resumes from the follower's ack
                    # rather than byte zero
                    return
                data = stream.read_at(offset, chunk)
                done = offset + len(data) >= total
                frame = {
                    "term": term, "leader": self.name,
                    "last_index": s_idx, "last_term": s_term,
                    "offset": offset, "total": total,
                    "crc32": zlib.crc32(data), "data": data, "done": done,
                    # configuration as of the snapshot index so a blank
                    # joiner learns the membership without any log prefix
                    "config": snap_config,
                }
                if repair:
                    frame["repair"] = True
                if done:
                    frame["stream_crc32"] = stream_crc
                    if repair:
                        frame["digest"] = expected_digest
                if chaos.active is not None \
                        and chaos.should("snapshot.chunk_drop"):
                    # frame lost in flight: re-probe the same offset — the
                    # follower's ack re-synchronizes the stream
                    drops += 1
                    if drops > 64:      # chaos armed at rate ~1.0
                        self._note_snap_failure(peer)
                        return
                    continue
                resp = self.transport.call(self.name, peer,
                                           "install_snapshot", frame)
                with self._lock:
                    if resp["term"] > self.term:
                        self._step_down(resp["term"])
                        return
                    if self.state != LEADER or self.term != term:
                        return
                if not resp.get("success"):
                    # follower could not persist/verify; back off instead
                    # of hammering it with the full stream every tick
                    self._note_snap_failure(peer)
                    return
                acked = resp.get("offset", offset + len(data))
                if done and acked >= total:
                    with self._lock:
                        if self.state != LEADER or self.term != term:
                            return
                        self._next_index[peer] = s_idx + 1
                        self._match_index[peer] = s_idx
                        self._peer_contact[peer] = time.monotonic()
                        self._snap_backoff.pop(peer, None)
                    if repair:
                        # verified True lifts the conviction; False
                        # keeps it (back off, then re-stream); absent
                        # (mixed-version follower that cannot verify)
                        # lifts it and lets the next checkpoint re-judge
                        verified = resp.get("verified")
                        self.integrity.repair_result(peer, verified)
                        if verified is False:
                            self._note_snap_failure(peer)
                    return
                if acked == offset:
                    # no progress (per-chunk CRC reject, or a done frame
                    # whose stream CRC failed when total == acked): give
                    # the link a rest after a few tries
                    stalls += 1
                    if stalls > 16:
                        self._note_snap_failure(peer)
                        return
                else:
                    stalls = 0
                    with self._lock:
                        # a moving stream is proof of contact: autopilot
                        # must not reap a peer mid-catch-up
                        self._peer_contact[peer] = time.monotonic()
                offset = min(max(acked, 0), total)
        except Unreachable:
            self._note_snap_failure(peer)
        except Exception:                           # noqa: BLE001
            log.warning("raft: %s snapshot stream to %s failed",
                        self.name, peer, exc_info=True)
            self._note_snap_failure(peer)
        finally:
            if stream is not None:
                stream.close()

    @requires_lock("_lock")
    def _advance_commit(self) -> None:
        """Majority-of-VOTERS match ⇒ commit (current-term entries only).

        The quorum is computed over the latest appended configuration —
        effective-on-append (§4.1) means a half-replicated AddVoter
        already raises the bar (2-of-4 can never commit), and a removed
        leader no longer counts itself.  Non-voters replicate but never
        advance commit."""
        race.read("RaftNode._voters", self)
        voters = self._voters or [self.name]
        matches = sorted(self._match_index.get(v, 0) for v in voters)
        quorum = len(voters) // 2 + 1
        majority = matches[len(voters) - quorum]
        if majority > self.commit_index \
                and self.log.term_at(majority) == self.term:
            self.commit_index = majority
            self._apply_cv.notify_all()
            self._commit_event.set()

    # ------------------------------------------------------------- apply

    def _run_apply(self) -> None:
        """One entry at a time: re-check state under the lock every step so
        a concurrently installed snapshot (which moves last_applied
        forward and compacts the log) can never be undone or spun on."""
        while not self._stop.is_set():
            with self._apply_cv:
                while self.last_applied >= self.commit_index \
                        and not self._stop.is_set():
                    self._apply_cv.wait(0.1)
                if self._stop.is_set():
                    return
                i = self.last_applied + 1
                e = self.log.get(i)
                if e is None:
                    if i <= self._last_snapshot_index:
                        # compacted: the snapshot already covers it
                        self.last_applied = i
                        continue
                    # not replicated yet; wait for it
                    self._apply_cv.wait(0.05)
                    continue
            with self._fsm_lock:
                with self._lock:
                    if i <= self.last_applied:   # snapshot raced us
                        continue
                    tctx = self._trace_notes.pop(i, None)
                tracer = tracing.active
                ta = time.time() if tctx is not None else 0.0
                try:
                    if chaos.active is not None \
                            and e.msg_type not in _APPLY_SKIP_EXEMPT \
                            and e.index > self._boot_log_end \
                            and chaos.should("fsm.apply_skip", self.name):
                        # injected divergence: the committed entry is
                        # silently NOT applied while last_applied still
                        # advances — the log says it happened, the state
                        # says it didn't.  Invisible to raft; only the
                        # integrity plane's digest checkpoints can tell.
                        log.warning("chaos: %s skipped fsm apply of %s "
                                    "at %d", self.name, e.msg_type,
                                    e.index)
                    else:
                        self.fsm.apply(e.index, e.msg_type, e.payload)
                    err = None
                except Exception as exc:           # noqa: BLE001
                    log.exception("fsm apply failed at %d", e.index)
                    err = exc
                if err is None and chaos.active is not None \
                        and e.index > self._boot_log_end \
                        and chaos.should("store.bitflip", self.name):
                    # injected silent corruption: flip one replicated
                    # record post-apply — no index bump, no dirty mark,
                    # caught only by a full digest walk
                    store = getattr(self.fsm, "store", None)
                    if store is not None \
                            and hasattr(store, "chaos_bitflip"):
                        hit = store.chaos_bitflip(chaos.active.uniform())
                        log.warning("chaos: %s bitflipped %s after "
                                    "apply %d", self.name, hit, e.index)
                if err is None and e.msg_type == STATE_CHECKPOINT_MSG \
                        and hasattr(self.fsm, "snapshot_tables"):
                    # digest stamped here, under _fsm_lock, so the walk
                    # sees exactly the state at this log position
                    try:
                        self.integrity.on_checkpoint(e.index, e.payload)
                    except Exception:               # noqa: BLE001
                        log.exception("integrity checkpoint at %d "
                                      "failed", e.index)
                if tctx is not None and tracer is not None:
                    # observe-time: timestamps taken around the FSM call,
                    # never inside it (the FSM must not read the clock)
                    tracer.emit(tctx, "raft.fsm_apply", ta, time.time(),
                                node=self.name, index=i,
                                msg_type=e.msg_type)
                with self._lock:
                    self.last_applied = max(self.last_applied, i)
                    fut = self._futures.pop(i, None)
                    # wake wait_applied() readers (the cv shares _lock)
                    self._apply_cv.notify_all()
                    # §4.2.2: a leader that committed its own removal
                    # steps down once the config entry APPLIES — the
                    # future was popped above so the caller still gets
                    # its success before _step_down fails the rest
                    if e.msg_type == CONFIGURATION_MSG \
                            and self.state == LEADER \
                            and self.name not in self._voters:
                        log.info("raft: %s removed from configuration; "
                                 "stepping down", self.name)
                        self._step_down(self.term)
            if fut is not None and not fut.done():
                if err is None:
                    fut.set_result(i)
                else:
                    fut.set_exception(err)

    # ------------------------------------------------------------- compaction

    def _maybe_compact(self) -> None:
        if self.snapshots is None:
            return
        with self._lock:
            if self.last_applied - self._last_snapshot_index \
                    < self.config.snapshot_threshold:
                return
        self.force_snapshot()

    def force_snapshot(self) -> None:
        """Operator snapshot save (command/raft_tools analogue).  Holds the
        FSM lock so the blob is exactly the state at `last_applied` — a
        concurrent apply landing mid-snapshot would make restart replay
        non-idempotent entries (e.g. job version bumps) twice."""
        if self.snapshots is None:
            return
        with self._fsm_lock:
            with self._lock:
                applied = self.last_applied
                term = self.log.term_at(applied) or self._last_snap_term \
                    or self.term
                cfg = self._config_at(applied)
            blob = self.fsm.snapshot()
        with self._lock:
            try:
                self.snapshots.save(applied, term, blob, config=cfg)
            except Exception:                       # noqa: BLE001
                # incl. injected snapshot.partial_write: the save did NOT
                # land durably, so compacting the log here would orphan
                # the only copy of those entries; keep the log and retry
                # at the next snapshot threshold
                log.warning("raft: %s snapshot save failed; keeping log",
                            self.name, exc_info=True)
                return
            self._last_snapshot_index = applied
            self._last_snap_term = term
            self._snap_config = cfg
            self.log.compact(applied)

    # ------------------------------------------------------------- RPC

    def _handle_rpc(self, method: str, args: dict) -> dict:
        if method == "request_vote":
            return self._on_request_vote(args)
        if method == "append_entries":
            return self._on_append_entries(args)
        if method == "install_snapshot":
            return self._on_install_snapshot(args)
        if method == "timeout_now":
            return self._on_timeout_now(args)
        raise ValueError(method)

    def _on_timeout_now(self, a: dict) -> dict:
        """TimeoutNow (§3.10): the current leader asks us to campaign
        immediately.  The election runs on its own thread — campaigning
        inline would hold the transport handler while we call every
        voter back through it."""
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False}
            if self.name not in self._voters:
                return {"term": self.term, "success": False}
            self._last_contact = time.monotonic()
        threading.Thread(target=self._transfer_campaign,
                         name=f"raft-transfer-{self.name}",
                         daemon=True).start()
        return {"term": self.term, "success": True}

    def _transfer_campaign(self) -> None:
        try:
            self._run_election(transfer=True)
        except Exception:                           # noqa: BLE001
            log.exception("raft: %s transfer campaign failed", self.name)

    def _on_request_vote(self, a: dict) -> dict:
        with self._lock:
            # leader stickiness (reference requestVote/requestPreVote):
            # while we are hearing from a live leader, refuse — and do NOT
            # adopt the candidate's term.  A partitioned or catching-up
            # node cannot depose a leader the quorum still follows.
            # Transfer votes (§3.10) bypass stickiness: the live leader
            # ITSELF asked this candidate to depose it.
            if not a.get("transfer") \
                    and self.leader_id is not None \
                    and self.leader_id != a["candidate"] \
                    and (time.monotonic() - self._last_contact
                         < self.config.election_timeout):
                return {"term": self.term, "granted": False}
            if a.get("prevote"):
                # would we vote for this candidate in that term?  No state
                # change, no disk: just an electability probe.
                my_last_term = self.log.last_term or self._last_snap_term
                granted = (a["term"] > self.term
                           and (a["last_log_term"] > my_last_term
                                or (a["last_log_term"] == my_last_term
                                    and a["last_log_index"]
                                    >= self.log.last_index)))
                return {"term": self.term, "granted": granted}
            if a["term"] > self.term:
                self._step_down(a["term"])
            granted = False
            if a["term"] == self.term \
                    and self.voted_for in (None, a["candidate"]):
                my_last_term = self.log.last_term or self._last_snap_term
                up_to_date = (
                    a["last_log_term"] > my_last_term
                    or (a["last_log_term"] == my_last_term
                        and a["last_log_index"] >= self.log.last_index))
                if up_to_date:
                    # grant only once the vote is on stable storage: a
                    # granted-then-forgotten vote is the two-leaders bug
                    self.voted_for = a["candidate"]
                    if self._persist_meta():
                        granted = True
                        self._last_contact = time.monotonic()
                    else:
                        self.voted_for = None
            return {"term": self.term, "granted": granted}

    def _on_append_entries(self, a: dict) -> dict:
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False,
                        "last_index": self.log.last_index}
            if a["term"] > self.term or self.state != FOLLOWER:
                self._step_down(a["term"])   # single term-adoption path
            self.leader_id = a["leader"]
            self._last_contact = time.monotonic()
            prev_index = a["prev_log_index"]
            if prev_index > 0:
                local_term = self.log.term_at(prev_index)
                if local_term == 0 and prev_index == self._last_snapshot_index:
                    local_term = self._last_snap_term
                if local_term != a["prev_log_term"] \
                        and prev_index > self._last_snapshot_index:
                    return {"term": self.term, "success": False,
                            "last_index": min(self.log.last_index,
                                              prev_index - 1)}
            # collect the fresh suffix, then append with ONE group-commit
            # durability wait (raft requires entries durable before this
            # response ACKs them — the leader counts us toward commit)
            fresh: List[LogEntry] = []
            for (idx, term, msg_type, payload) in a["entries"]:
                if not fresh:
                    existing = self.log.get(idx)
                    if existing is not None and existing.term == term:
                        continue
                fresh.append(LogEntry(idx, term, msg_type, payload))
            self.log.append_batch(fresh)
            if fresh:
                if fresh[0].index <= self._config_index:
                    # the conflicting suffix we just truncated carried the
                    # configuration we were running; fall back to what
                    # storage still holds before adopting the new entries
                    self._recompute_config()
                for e in fresh:
                    if e.msg_type == CONFIGURATION_MSG:
                        # effective on append (§4.1), commit not required
                        self._set_config(e.payload["voters"],
                                         e.payload["nonvoters"], e.index)
            if a["leader_commit"] > self.commit_index:
                self.commit_index = min(a["leader_commit"],
                                        self.log.last_index)
                self._apply_cv.notify_all()
            if a.get("integrity_quarantine"):
                # the leader's majority vote convicted us: stop serving
                # stale/lease reads now, keep replicating and voting —
                # the repair snapshot stream is already on its way
                self.integrity.quarantine(
                    "leader divergence verdict (table %s)"
                    % a["integrity_quarantine"])
            resp = {"term": self.term, "success": True,
                    "last_index": self.log.last_index}
            rep = self.integrity.report()
            if rep is not None:
                # digest piggyback: {index, digest, per_table} of our
                # newest applied STATE_CHECKPOINT (absent before the
                # first one — the leader counts that as "unverified")
                resp["integrity"] = rep
            return resp

    def _on_install_snapshot(self, a: dict) -> dict:
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False}
            if a["term"] > self.term or self.state != FOLLOWER:
                self._step_down(a["term"])   # single term-adoption path
            self.leader_id = a["leader"]
            self._last_contact = time.monotonic()
        if "offset" not in a:
            # monolithic install (seed protocol, kept for compatibility):
            # the whole blob arrives in one frame
            return self._install_snapshot_blob(a, a["data"])
        return self._on_snapshot_chunk(a)

    def _on_snapshot_chunk(self, a: dict) -> dict:
        """One frame of a chunked InstallSnapshot stream.

        Frames append to a temp file through a ChunkSink keyed by the
        snapshot identity (last_index, last_term, total); every ack
        carries our next expected offset, which is the whole resume
        protocol — a duplicated or reordered frame acks the current
        offset, a restarted follower (no sink) acks 0, and a restarted
        leader re-syncs off the first ack.  The sink deliberately
        survives leader/term changes: a new leader streaming the SAME
        snapshot resumes where the dead one stopped, while a different
        snapshot identity discards the partial sink cleanly.  On `done`
        the whole-stream CRC gates persist-before-accept."""
        key = (a["last_index"], a["last_term"], a["total"])
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False, "offset": 0}
            sink = self._snap_rx
            if sink is not None and sink.key != key:
                # a different snapshot supersedes the partial stream
                sink.abort()
                sink = self._snap_rx = None
            if sink is None:
                if a["offset"] != 0:
                    # mid-stream frame with no sink (we restarted):
                    # tell the leader to resume from byte zero
                    return {"term": self.term, "success": True,
                            "offset": 0}
                try:
                    sink = self._snap_rx = ChunkSink(
                        self.snapshots.dir if self.snapshots is not None
                        else None, key)
                except OSError:
                    log.warning("raft: %s cannot open snapshot sink",
                                self.name, exc_info=True)
                    return {"term": self.term, "success": False,
                            "offset": 0}
            if a["offset"] != sink.offset:
                # dropped/duplicated/reordered frame: re-sync the leader
                # to where the stream actually is
                return {"term": self.term, "success": True,
                        "offset": sink.offset}
            if zlib.crc32(a["data"]) != a["crc32"]:
                # corrupt in flight: ask for the same offset again
                return {"term": self.term, "success": True,
                        "offset": sink.offset}
            try:
                sink.append(a["data"])
            except OSError:
                log.warning("raft: %s snapshot chunk append failed",
                            self.name, exc_info=True)
                self._snap_rx = None
                sink.abort()
                return {"term": self.term, "success": False, "offset": 0}
            if not a.get("done"):
                return {"term": self.term, "success": True,
                        "offset": sink.offset}
            # final frame: assemble + whole-stream verify, then hand the
            # blob to the monolithic install tail below (outside _lock —
            # it takes _fsm_lock first, same nesting as force_snapshot)
            self._snap_rx = None
            data = sink.finish()
            if sink.offset != a["total"] \
                    or sink.crc != a.get("stream_crc32", sink.crc):
                # the assembled bytes are not the leader's blob (e.g. a
                # resumed prefix from a dead leader whose snapshot bytes
                # differ): discard and restart from zero
                return {"term": self.term, "success": True, "offset": 0}
        resp = self._install_snapshot_blob(a, data)
        resp["offset"] = a["total"] if resp.get("success") else 0
        return resp

    def _install_snapshot_blob(self, a: dict, data: bytes) -> dict:
        """Persist-before-accept + restore of a complete snapshot blob —
        the tail of the install path, reached monolithically or when a
        chunk stream's `done` frame verifies."""
        with self._lock:
            if a["term"] < self.term:
                return {"term": self.term, "success": False}
            # Persist BEFORE accepting.  The snapshot stands in for log
            # entries the leader has already compacted away: if we restore
            # it in memory without a durable copy, later appends land past
            # a hole that exists only on disk, and the next restart replays
            # around the hole — committed state silently vanishes.  Reject
            # instead; the leader backs off and retries the install.
            if self.snapshots is not None:
                try:
                    self.snapshots.save(a["last_index"], a["last_term"],
                                        data, config=a.get("config"))
                except Exception:                   # noqa: BLE001
                    log.warning("raft: %s could not persist installed "
                                "snapshot; rejecting (leader retries)",
                                self.name, exc_info=True)
                    return {"term": self.term, "success": False}
        # fsm_lock outer, _lock inner (same nesting as force_snapshot):
        # last_applied must move in the same critical section as the
        # restore or the apply loop could re-apply a pre-snapshot entry
        # onto the restored state
        repair = bool(a.get("repair"))
        with self._fsm_lock:
            with self._lock:
                if repair:
                    if a["last_index"] < self._last_snapshot_index:
                        # a repair rewind below our own compaction point
                        # has no log tail left to replay through: reject
                        # so the leader retries with a fresher snapshot
                        return {"term": self.term, "success": False}
                    # anti-entropy repair bypasses both guards below:
                    # our state at these indexes is exactly what is
                    # suspected corrupt, so "already covered" means
                    # nothing — wipe and rebuild from the leader's blob
                    skip_restore = False
                else:
                    if a["last_index"] <= self._last_snapshot_index:
                        # duplicate/stale install: never regress the FSM
                        return {"term": self.term, "success": True}
                    # §7: if the apply loop already covered the
                    # snapshot's prefix via AppendEntries while the
                    # stream was in flight, the state ALREADY includes
                    # it (committed entries at an index are unique) —
                    # restoring would rewind the FSM past entries that
                    # will never re-apply.  Retain the state, still
                    # compact the now-redundant log prefix below.
                    skip_restore = a["last_index"] <= self.last_applied
            if repair:
                # a repair stream IS the divergence verdict (it can
                # outrun the quarantine directive riding our next
                # append): refuse local reads from here until the
                # restored state digest-verifies
                self.integrity.quarantine(
                    "anti-entropy repair in progress (leader divergence "
                    "verdict)")
            if not skip_restore:
                self.fsm.restore(data)
                self.integrity.note_restore()
                if chaos.active is not None \
                        and chaos.should("disk.silent_corrupt", self.name):
                    # injected silent disk corruption: the restored
                    # state differs from the streamed blob (a bad read
                    # that still unpickled) — digest verification below
                    # must refuse re-admission and the leader retries
                    store = getattr(self.fsm, "store", None)
                    if store is not None \
                            and hasattr(store, "chaos_bitflip"):
                        hit = store.chaos_bitflip(chaos.active.uniform())
                        log.warning("chaos: %s silent-corrupted %s on "
                                    "snapshot restore", self.name, hit)
            verified = None
            if repair and hasattr(self.fsm, "snapshot_tables"):
                # digest-verified re-admission: recompute the restored
                # state's digest and match the leader's expected one —
                # still under _fsm_lock, so the walk is quiescent
                verified = self.integrity.verify_restore(a.get("digest"))
            if repair and verified is None:
                # no digest to verify against (mixed-version leader):
                # the install itself was CRC-gated — do not brick the
                # replica behind a verdict nobody can verify; the next
                # checkpoint vote re-judges the restored state
                self.integrity.clear_quarantine(
                    "repair installed (no digest to verify)")
            with self._lock:
                self._last_snapshot_index = a["last_index"]
                self._last_snap_term = a["last_term"]
                self.log.compact(a["last_index"])
                if repair:
                    # rewind-and-replay: the restored blob IS the state
                    # at the snapshot index; committed entries above it
                    # re-apply onto the clean base (exactly-once writes
                    # are deduped by replicated state, e.g.
                    # _applied_plan_ids)
                    self.last_applied = a["last_index"]
                    self._apply_cv.notify_all()
                else:
                    self.last_applied = max(self.last_applied,
                                            a["last_index"])
                self.commit_index = max(self.commit_index, a["last_index"])
                cfg = a.get("config")
                if cfg:
                    self._snap_config = cfg
                    if cfg.get("index", 0) >= self._config_index:
                        # a blank joiner learns the membership here; an
                        # established follower only moves FORWARD (a log
                        # tail past the snapshot may hold a newer config)
                        self._set_config(cfg["voters"],
                                         cfg.get("nonvoters", []),
                                         cfg.get("index", 0))
                resp = {"term": self.term, "success": True}
                if repair:
                    resp["verified"] = verified
                return resp
