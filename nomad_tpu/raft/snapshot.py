"""Snapshot store (reference: raft snapshots + `nomad operator snapshot
save/restore`, helper/snapshot/ and command/raft_tools/).

Snapshots are (term, index, fsm blob) files in a directory; `latest()`
returns the newest *valid* one for restart/restore, old snapshots are
reaped keeping `retain`.

Crash safety: each file is a checksummed record (8-byte magic
``NTPUSNP1`` + ``[u32 len][u32 crc32][payload]``) written
write-temp → fsync → atomic rename → directory fsync, so a crash
mid-save leaves the previous snapshot untouched.  `latest()` verifies
the checksum and falls back to an older retained snapshot when the
newest is torn/corrupt (the window chaos point `snapshot.partial_write`
injects), and `_reap` never deletes the newest valid snapshot — even
when retention is misconfigured to 0, the restart anchor survives.

Seed-era bare-pickle snapshots remain readable (no checksum to verify,
best-effort parse) so existing data dirs upgrade in place.
"""
from __future__ import annotations

import logging
import os
import pickle
import struct
import tempfile
import threading
import zlib
from typing import Optional, Tuple

from nomad_tpu import chaos
from nomad_tpu.raft.log import fsync_dir

log = logging.getLogger(__name__)

SNAP_MAGIC = b"NTPUSNP1"
_HDR = struct.Struct("<II")


class ChunkSink:
    """Temp-file assembler for one inbound chunked InstallSnapshot
    stream (dissertation §7).  Frames append sequentially: `offset` is
    the next expected byte (the resume ack), `crc` the running
    whole-stream CRC.  `finish()` flushes and returns the assembled
    blob for the persist-before-accept path; `abort()` discards the
    temp file.  The file lives beside the snapshot store (same
    filesystem as the final record) or in the system temp dir for
    storeless nodes — either way it is scratch state: the durable copy
    is only ever written by FileSnapshotStore.save()."""

    def __init__(self, directory: Optional[str], key: tuple):
        self.key = key          # (last_index, last_term, total)
        self.offset = 0
        self.crc = 0
        fd, self.path = tempfile.mkstemp(dir=directory,
                                         prefix=".snap-rx-")
        self._fh = os.fdopen(fd, "wb")

    def append(self, data: bytes) -> None:
        self._fh.write(data)
        self.offset += len(data)
        self.crc = zlib.crc32(data, self.crc)

    def finish(self) -> bytes:
        self._fh.flush()
        self._fh.close()
        try:
            with open(self.path, "rb") as fh:
                return fh.read()
        finally:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def abort(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class SnapshotStream:
    """Windowed read handle over a snapshot's sidecar blob file for the
    outbound InstallSnapshot path.  The sender never materializes the
    whole blob: `read_at` serves frames out of a sliding buffer of at
    most `window_bytes` (NOMAD_TPU_SNAP_WINDOW frames' worth), refilled
    from disk as the follower's acks advance.  `peak_buffered` records
    the high-water mark so tests can assert the bound holds."""

    def __init__(self, path: str, index: int, term: int, total: int,
                 stream_crc: int, config: Optional[dict],
                 window_bytes: int):
        self.path = path
        self.index = index
        self.term = term
        self.total = total
        self.stream_crc = stream_crc
        self.config = config
        self.window_bytes = max(1, int(window_bytes))
        self._buf = b""
        self._buf_off = 0
        self.peak_buffered = 0

    def read_at(self, offset: int, n: int) -> bytes:
        """`n` bytes at `offset` (short at EOF).  Acks can regress the
        offset (retransmit) or jump it forward; any miss refills the
        window from disk at the requested offset."""
        offset = max(0, min(offset, self.total))
        n = min(n, self.total - offset)
        end = offset + n
        if not (self._buf_off <= offset
                and end <= self._buf_off + len(self._buf)):
            with open(self.path, "rb") as fh:
                fh.seek(offset)
                self._buf = fh.read(max(n, self.window_bytes))
            self._buf_off = offset
            self.peak_buffered = max(self.peak_buffered, len(self._buf))
        lo = offset - self._buf_off
        return self._buf[lo:lo + n]

    def close(self) -> None:
        self._buf = b""


class FileSnapshotStore:
    # wait-graph (nomad_tpu.analysis)
    _LOCK_BLOCKING_OK = {
        "_lock": "save serializes write+fsync+rename so readers only "
                 "ever list completed snapshots",
    }

    def __init__(self, directory: str, retain: int = 2):
        self.dir = directory
        self.retain = retain
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        # a crash mid-stream orphans the receiving ChunkSink's temp
        # file; the restarted node acks offset 0 and re-streams, so the
        # orphan is pure garbage — reap it here.  Likewise a sidecar
        # blob whose record never landed (crash between sidecar write
        # and record rename) is garbage.
        names = os.listdir(directory)
        for stale in names:
            if stale.startswith(".snap-rx-"):
                try:
                    os.unlink(os.path.join(directory, stale))
                except OSError:
                    pass
            elif stale.endswith(".snap.blob") and \
                    stale[:-len(".blob")] not in names:
                try:
                    os.unlink(os.path.join(directory, stale))
                except OSError:
                    pass

    def save(self, index: int, term: int, blob: bytes,
             config: Optional[dict] = None) -> str:
        with self._lock:
            name = f"snapshot-{term:010d}-{index:012d}.snap"
            path = os.path.join(self.dir, name)
            rec_dict = {"index": index, "term": term, "data": blob}
            if config is not None:
                # cluster configuration as of `index` (Raft §4.1): a
                # joiner restored from this snapshot alone must still
                # learn the membership
                rec_dict["config"] = config
            payload = pickle.dumps(rec_dict,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            rec = SNAP_MAGIC + _HDR.pack(len(payload),
                                         zlib.crc32(payload)) + payload
            if chaos.active is not None \
                    and chaos.should("snapshot.partial_write"):
                # crash mid-save: a truncated record lands under the
                # final name (rename committed, data blocks lost — the
                # no-fsync window this store's fsyncs close).  latest()
                # must skip it; the caller must treat the save as failed.
                reg = chaos.active
                frac = reg.uniform() if reg is not None else 0.5
                cut = min(len(rec) - 1,
                          max(len(SNAP_MAGIC) + 1, int(len(rec) * frac)))
                fd, tmp = tempfile.mkstemp(dir=self.dir,
                                           prefix=".snap-tmp-")
                with os.fdopen(fd, "wb") as fh:
                    fh.write(rec[:cut])
                os.replace(tmp, path)
                raise chaos.ChaosError("snapshot.partial_write")
            # sidecar blob FIRST (the streaming path reads frames off
            # disk from it instead of holding the whole blob in memory
            # per peer stream); written while `blob` is in memory here
            # anyway, so save() costs no extra buffering.  Ordering: a
            # crash after the sidecar but before the record rename
            # leaves an orphan .blob, reaped at the next startup.
            self._write_atomic(path + ".blob", blob)
            fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".snap-tmp-")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(rec)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            fsync_dir(path)
            self._reap()
            return path

    def _write_atomic(self, path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".snap-tmp-")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _read(self, path: str) -> Optional[dict]:
        """Parse + verify one snapshot file; None if torn/corrupt."""
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return None
        if not data.startswith(SNAP_MAGIC):
            # legacy bare-pickle snapshot (seed format): best-effort
            try:
                rec = pickle.loads(data)
            except Exception:                       # noqa: BLE001
                return None
            if isinstance(rec, dict) and {"index", "term",
                                          "data"} <= rec.keys():
                return rec
            return None
        if len(data) < len(SNAP_MAGIC) + _HDR.size:
            return None
        ln, crc = _HDR.unpack_from(data, len(SNAP_MAGIC))
        body = data[len(SNAP_MAGIC) + _HDR.size:]
        if len(body) != ln:
            return None
        for attempt in (0, 1):
            payload = body
            if attempt == 0 and chaos.active is not None \
                    and payload and chaos.should("disk.corrupt_read"):
                payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
            if zlib.crc32(payload) == crc:
                try:
                    return pickle.loads(payload)
                except Exception:                   # noqa: BLE001
                    return None
            log.warning("snapshot: CRC mismatch reading %s (attempt %d); "
                        "retrying read", path, attempt + 1)
        return None

    def _snap_names(self):
        return sorted(f for f in os.listdir(self.dir)
                      if f.endswith(".snap"))

    def _reap(self) -> None:
        snaps = self._snap_names()
        # the newest VALID snapshot is the restart anchor: never reap it,
        # even when retention is misconfigured to 0 or the newest files
        # are corrupt
        newest_valid = None
        for name in reversed(snaps):
            if self._read(os.path.join(self.dir, name)) is not None:
                newest_valid = name
                break
        keep = max(self.retain, 1)
        for old in snaps[:-keep]:
            if old == newest_valid:
                continue
            os.unlink(os.path.join(self.dir, old))
            try:
                os.unlink(os.path.join(self.dir, old + ".blob"))
            except OSError:
                pass

    def latest(self) -> Optional[Tuple[int, int, bytes]]:
        rec = self.latest_full()
        if rec is None:
            return None
        return rec["index"], rec["term"], rec["data"]

    def latest_full(self) -> Optional[dict]:
        """The newest valid snapshot as its full record dict — including
        the optional `config` key that `latest()`'s legacy 3-tuple cannot
        carry."""
        with self._lock:
            for name in reversed(self._snap_names()):
                rec = self._read(os.path.join(self.dir, name))
                if rec is None:
                    log.warning("snapshot: skipping corrupt/torn %s; "
                                "falling back to an older snapshot", name)
                    continue
                return rec
            return None

    def open_stream(self, window_bytes: int) -> Optional[SnapshotStream]:
        """Open the newest valid snapshot for outbound streaming: a
        :class:`SnapshotStream` whose frames come off the sidecar blob
        file in a sliding `window_bytes` buffer — the per-peer memory
        bound for InstallSnapshot.  The record is parsed ONCE here (for
        CRC verification and meta); the transient blob is dropped before
        streaming starts.  Pre-sidecar snapshots (seed-era data dirs)
        have the sidecar materialized from the record on first open."""
        with self._lock:
            for name in reversed(self._snap_names()):
                path = os.path.join(self.dir, name)
                rec = self._read(path)
                if rec is None:
                    continue
                blob = rec["data"]
                side = path + ".blob"
                try:
                    if not os.path.exists(side) or \
                            os.path.getsize(side) != len(blob):
                        self._write_atomic(side, blob)
                except OSError:
                    return None
                stream = SnapshotStream(
                    side, rec["index"], rec["term"], len(blob),
                    zlib.crc32(blob), rec.get("config"), window_bytes)
                del rec, blob      # nothing but the window stays resident
                return stream
            return None
