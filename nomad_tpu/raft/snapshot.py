"""Snapshot store (reference: raft snapshots + `nomad operator snapshot
save/restore`, helper/snapshot/ and command/raft_tools/).

Snapshots are (term, index, fsm blob) files in a directory; `latest()`
returns the newest for restart/restore, old snapshots are reaped keeping
`retain`.
"""
from __future__ import annotations

import os
import pickle
import tempfile
import threading
from typing import Optional, Tuple


class FileSnapshotStore:
    def __init__(self, directory: str, retain: int = 2):
        self.dir = directory
        self.retain = retain
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    def save(self, index: int, term: int, blob: bytes) -> str:
        with self._lock:
            name = f"snapshot-{term:010d}-{index:012d}.snap"
            path = os.path.join(self.dir, name)
            fd, tmp = tempfile.mkstemp(dir=self.dir)
            with os.fdopen(fd, "wb") as fh:
                pickle.dump({"index": index, "term": term, "data": blob}, fh)
            os.replace(tmp, path)
            self._reap()
            return path

    def _reap(self) -> None:
        snaps = sorted(f for f in os.listdir(self.dir) if f.endswith(".snap"))
        for old in snaps[:-self.retain] if self.retain else []:
            os.unlink(os.path.join(self.dir, old))

    def latest(self) -> Optional[Tuple[int, int, bytes]]:
        with self._lock:
            snaps = sorted(f for f in os.listdir(self.dir)
                           if f.endswith(".snap"))
            if not snaps:
                return None
            with open(os.path.join(self.dir, snaps[-1]), "rb") as fh:
                rec = pickle.load(fh)
            return rec["index"], rec["term"], rec["data"]
