"""In-memory Raft transport (reference: raftInmem / the TCP raftLayer,
nomad/raft_rpc.go — here an in-process registry so multi-server clusters
boot without real sockets, exactly like nomad.TestServer's in-memory Raft,
nomad/testing.go:41-47).

Payloads are pickle round-tripped so servers never share mutable structs —
the same isolation a real wire gives.
"""
from __future__ import annotations

import pickle
import threading
from typing import Callable, Dict, Set


class Unreachable(Exception):
    pass


class InMemTransport:
    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._down: Set[str] = set()
        self._partitions: Dict[str, Set[str]] = {}

    def register(self, name: str, handler: Callable[[str, dict], dict]) -> None:
        with self._lock:
            self._handlers[name] = handler

    def deregister(self, name: str) -> None:
        with self._lock:
            self._handlers.pop(name, None)

    # --- fault injection -------------------------------------------------

    def set_down(self, name: str, down: bool = True) -> None:
        with self._lock:
            (self._down.add if down else self._down.discard)(name)

    def partition(self, a: str, b: str, cut: bool = True) -> None:
        """Cut (or heal) the link between two members."""
        with self._lock:
            if cut:
                self._partitions.setdefault(a, set()).add(b)
                self._partitions.setdefault(b, set()).add(a)
            else:
                self._partitions.get(a, set()).discard(b)
                self._partitions.get(b, set()).discard(a)

    # --- RPC -------------------------------------------------------------

    def call(self, src: str, dst: str, method: str, args: dict) -> dict:
        with self._lock:
            handler = self._handlers.get(dst)
            blocked = (dst in self._down or src in self._down
                       or dst in self._partitions.get(src, ()))
        if handler is None or blocked:
            raise Unreachable(f"{src}->{dst}")
        # wire round-trip: no shared mutable state between servers
        args = pickle.loads(pickle.dumps(args))
        out = handler(method, args)
        return pickle.loads(pickle.dumps(out))
