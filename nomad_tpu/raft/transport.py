"""Raft transports.

InMemTransport — in-process registry (reference raftInmem,
nomad/testing.go:41-47) so multi-server clusters boot without sockets;
payloads are pickle round-tripped so servers never share mutable structs.

TcpTransport — the production analog of the reference's TCP raftLayer +
msgpack-RPC (nomad/raft_rpc.go, nomad/rpc.go): one listener per process,
HMAC-authenticated length-prefixed frames (the same framing as
nomad_tpu.rpc.tcp), an address book mapping member names to (host, port)
that gossip keeps fresh, and per-destination pooled connections.  Both
transports expose the same surface — register(name, handler) /
call(src, dst, method, args) — so RaftNode, Server.rpc_leader and the
RemoteWorkers run unchanged over either.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import threading
from typing import Callable, Dict, Optional, Set, Tuple

from nomad_tpu import chaos


class Unreachable(Exception):
    pass


_RAFT_METHODS = frozenset(
    {"request_vote", "append_entries", "install_snapshot", "timeout_now"})


def _chaos_check(src: str, dst: str, method: str) -> None:
    """Shared transport fault points: rpc.drop hits any remote call,
    raft.partition only consensus traffic."""
    reg = chaos.active
    if reg is None or src == dst:
        return
    chaos.maybe_delay()
    if reg.should("rpc.drop"):
        raise Unreachable(f"{src}->{dst}: chaos rpc.drop")
    if method in _RAFT_METHODS and reg.should("raft.partition"):
        raise Unreachable(f"{src}->{dst}: chaos raft.partition")


class InMemTransport:
    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._down: Set[str] = set()
        self._partitions: Dict[str, Set[str]] = {}

    def register(self, name: str, handler: Callable[[str, dict], dict]) -> None:
        with self._lock:
            self._handlers[name] = handler

    def deregister(self, name: str) -> None:
        with self._lock:
            self._handlers.pop(name, None)

    # --- fault injection -------------------------------------------------

    def set_down(self, name: str, down: bool = True) -> None:
        with self._lock:
            (self._down.add if down else self._down.discard)(name)

    def partition(self, a: str, b: str, cut: bool = True) -> None:
        """Cut (or heal) the link between two members."""
        with self._lock:
            if cut:
                self._partitions.setdefault(a, set()).add(b)
                self._partitions.setdefault(b, set()).add(a)
            else:
                self._partitions.get(a, set()).discard(b)
                self._partitions.get(b, set()).discard(a)

    # --- RPC -------------------------------------------------------------

    def call(self, src: str, dst: str, method: str, args: dict) -> dict:
        # fault checks run on MEMBER names ("server-1"), not handler
        # names ("rpc:server-1"/"wan:server-1") — a downed or partitioned
        # member loses all of its channels at once, matching a real
        # network cut
        src_m, dst_m = _member_of(src), _member_of(dst)
        with self._lock:
            handler = self._handlers.get(dst)
            blocked = (dst_m in self._down or src_m in self._down
                       or dst_m in self._partitions.get(src_m, ()))
        if handler is None or blocked:
            raise Unreachable(f"{src}->{dst}")
        if chaos.active is not None:
            _chaos_check(src, dst, method)
        # wire round-trip: no shared mutable state between servers
        args = pickle.loads(pickle.dumps(args))
        out = handler(method, args)
        return pickle.loads(pickle.dumps(out))


class _TcpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        from nomad_tpu.rpc.tcp import _recv_frame, _send_frame
        t: "TcpTransport" = self.server.transport       # type: ignore
        sock = self.request
        sock.settimeout(60.0)
        try:
            while True:
                req = _recv_frame(sock, t._secret)
                dst, method, args = req["dst"], req["method"], req["args"]
                handler = t._local(dst)
                try:
                    if handler is None:
                        raise Unreachable(f"no local handler for {dst}")
                    result = handler(method, args)
                    _send_frame(sock, {"ok": True, "result": result},
                                t._secret)
                except Exception as e:              # noqa: BLE001
                    # frames are HMAC-authenticated, so peers are trusted:
                    # ship the exception itself for faithful re-raise
                    _send_frame(sock, {"ok": False, "exc": e}, t._secret)
        except (ConnectionError, OSError, EOFError):
            return


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TcpTransport:
    """Network transport: same surface as InMemTransport over real
    sockets.  One instance per process; all of the process's handlers
    (raft + rpc:*) share the listener."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: bytes = b""):
        from nomad_tpu.rpc.tcp import _NO_SECRET
        if not secret and host not in ("127.0.0.1", "localhost", "::1"):
            raise ValueError("refusing to bind beyond loopback without "
                             "a cluster secret")
        self._secret = secret or _NO_SECRET
        self._lock = threading.Lock()
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._addrs: Dict[str, Tuple[str, int]] = {}
        self._pool: Dict[Tuple[str, int], socket.socket] = {}
        self._srv = _TcpServer((host, port), _TcpHandler)
        self._srv.transport = self
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="raft-tcp", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- admin

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.server_address[:2]

    def register(self, name: str, handler) -> None:
        with self._lock:
            self._handlers[name] = handler
            self._addrs[_member_of(name)] = self.address

    def deregister(self, name: str) -> None:
        with self._lock:
            self._handlers.pop(name, None)

    def add_peer(self, name: str, addr: Tuple[str, int]) -> None:
        """Seed / refresh a member's address (gossip calls this as it
        learns addresses)."""
        with self._lock:
            self._addrs[_member_of(name)] = tuple(addr)

    def peer_addr(self, name: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._addrs.get(_member_of(name))

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        with self._lock:
            for s in self._pool.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._pool.clear()
            self._handlers.clear()

    def _local(self, dst: str):
        with self._lock:
            return self._handlers.get(dst)

    # ------------------------------------------------------------- call

    def call(self, src: str, dst: str, method: str, args: dict) -> dict:
        from nomad_tpu.rpc.tcp import _recv_frame, _send_frame

        if chaos.active is not None:
            _chaos_check(src, dst, method)
        handler = self._local(dst)
        if handler is not None:
            # local shortcut still round-trips through pickle so local
            # and remote calls have identical aliasing semantics
            args = pickle.loads(pickle.dumps(args))
            return pickle.loads(pickle.dumps(handler(method, args)))
        addr = self.peer_addr(dst)
        if addr is None:
            raise Unreachable(f"{src}->{dst}: unknown address")
        with self._lock:
            sock = self._pool.pop(addr, None)
        for attempt in (0, 1):
            if sock is None:
                try:
                    sock = socket.create_connection(addr, timeout=5.0)
                    sock.settimeout(10.0)
                except OSError as e:
                    raise Unreachable(f"{src}->{dst}: {e}") from e
            try:
                _send_frame(sock, {"dst": dst, "method": method,
                                   "args": args}, self._secret)
                resp = _recv_frame(sock, self._secret)
                break
            except (ConnectionError, OSError) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                sock = None
                if attempt == 1:
                    raise Unreachable(f"{src}->{dst}: {e}") from e
        with self._lock:
            prev = self._pool.get(addr)
            if prev is None:
                self._pool[addr] = sock
            else:
                sock.close()
        if resp.get("ok"):
            return resp["result"]
        raise resp["exc"]


def _member_of(name: str) -> str:
    """Handler names "server-1" and "rpc:server-1" share one address."""
    return name.split(":", 1)[1] if ":" in name else name
