"""Replica-integrity plane: log-stamped state digests, divergence
quarantine, and anti-entropy self-repair.

Paxos Made Live (Chandra et al., PODC '07) describes periodic
log-stamped state checksums catching real replica-divergence bugs in
production Chubby cells; Dynamo (DeCandia et al., SOSP '07) repairs the
inconsistency it detects with anti-entropy.  This module is both ideas
on top of the machinery the repo already has: the byte-identity
encoding the scenario battery gates on (state/digest.py) and the
resumable chunked InstallSnapshot stream as the repair channel.

Protocol:

- The leader periodically proposes a ``STATE_CHECKPOINT`` log entry
  (core/server.py `_integrity_loop`), stamped at PROPOSE time — the FSM
  itself never reads the clock, so the entry applies as a deterministic
  no-op on every replica.
- At apply, every replica computes per-table digests of the replicated
  tables over the canonical snapshot encoding (`on_checkpoint`).
  Digests are incrementally maintained: FSM apply hooks mark the tables
  each message type touches dirty, clean tables reuse the cached
  digest, and every ``NOMAD_TPU_INTEGRITY_FULL_EVERY``-th checkpoint
  full-walks all tables — the full walk is ground truth and catches
  silent corruption (a bit flip marks nothing dirty).
- Followers piggyback ``{index, digest, per_table}`` on heartbeat-ack
  responses; the leader votes digest-equality by MAJORITY at each
  checkpoint index.  An ack WITHOUT the digest field (a mixed-version
  peer mid rolling-upgrade) is "unverified": counted, never judged — a
  healthy old replica must never be false-positive repaired.
- A mismatch at an INCREMENTAL checkpoint raises the integrity alarm
  and escalates: the very next proposal is a full walk.  A mismatch at
  a FULL checkpoint convicts: the minority replica is divergent.  The
  two-step keeps a stale per-type dirty map from ever convicting a
  healthy replica — conviction only happens on ground truth.
- A convicted follower self-quarantines (serving/gate.py refuses
  stale/lease reads with a ``quarantined`` hint, autopilot sees it
  unhealthy) while still replicating and voting; the leader streams a
  repair snapshot that wipes and rebuilds its FSM, and the follower
  re-admits itself only after recomputing the digest of the restored
  state and matching the leader's expected digest (`verify_restore`).
  A divergent LEADER (it lost the majority vote) quarantines its own
  reads and hands leadership off so it can be repaired as a follower.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from nomad_tpu.state import digest as state_digest
from nomad_tpu.telemetry import global_metrics

log = logging.getLogger("nomad_tpu.raft.integrity")

# Tables recomputed at EVERY checkpoint regardless of dirty marks:
# scalars too cheap to track that change on almost every apply.
_ALWAYS_DIRTY = frozenset({"latest_index", "extra"})


class IntegrityTracker:
    """Per-replica integrity state: the local checkpoint digest, the
    quarantine flag, and (on the leader) the per-peer report table the
    majority vote runs over.  Owned by a RaftNode; all shared state
    lives under `_lock` (leaf lock — never held across a digest walk or
    any raft call)."""

    _LOCK_NAME = "_lock"

    def __init__(self, node):
        self._node = node
        self._lock = threading.Lock()
        # local digest state
        self._cache: Dict[str, str] = {}
        self._dirty: Dict[str, bool] = {}
        self._all_dirty = True          # boot: first checkpoint full-walks
        self.last: Optional[dict] = None
        # quarantine
        self.quarantined = False
        self.quarantine_reason = ""
        # leader-side vote state
        self._reports: Dict[str, dict] = {}
        self._unverified: Dict[str, int] = {}
        self._divergent: Dict[str, str] = {}   # peer -> first divergent table
        self._alarmed_index = -1
        self._escalate = threading.Event()
        self.counters: Dict[str, int] = {
            "checkpoints": 0, "full_walks": 0, "alarms": 0,
            "quarantines": 0, "repairs_started": 0, "repairs_verified": 0,
            "unverified_acks": 0,
        }

    # ------------------------------------------------------- local digests

    def note_dirty(self, tables) -> None:
        """FSM apply hook: mark the tables an applied entry may have
        touched (None = everything, the conservative default)."""
        with self._lock:
            if tables is None:
                self._all_dirty = True
                return
            for name in tables:
                self._dirty[name] = True

    def note_restore(self) -> None:
        """A snapshot install replaced the store wholesale: the digest
        cache is void and there is no current checkpoint."""
        with self._lock:
            self._cache = {}
            self._dirty = {}
            self._all_dirty = True
            self.last = None

    def on_checkpoint(self, index: int, payload: dict) -> dict:
        """Compute this replica's digest at a STATE_CHECKPOINT apply.
        Runs on the apply thread under the node's fsm lock, so the walk
        sees a quiescent store; only bookkeeping takes `_lock`."""
        tables = self._node.fsm.snapshot_tables()
        with self._lock:
            full = bool(payload.get("full")) or self._all_dirty
            dirty = set(self._dirty)
            self._dirty = {}
            self._all_dirty = False
            cache = self._cache
        per: Dict[str, str] = {}
        for name in sorted(tables):
            if full or name in dirty or name in _ALWAYS_DIRTY \
                    or name not in cache:
                per[name] = state_digest.table_digest(tables[name])
            else:
                per[name] = cache[name]
        overall = state_digest.combine(per)
        rec = {"index": index, "digest": overall, "per_table": per,
               "full": full, "seq": int(payload.get("seq", 0))}
        with self._lock:
            self._cache = per
            self.last = rec
            self.counters["checkpoints"] += 1
            if full:
                self.counters["full_walks"] += 1
        global_metrics.incr("integrity.checkpoint")
        if full:
            global_metrics.incr("integrity.full_walk")
        global_metrics.set_gauge("integrity.last_index", float(index))
        return rec

    def report(self) -> Optional[dict]:
        """The `{index, digest, per_table}` record piggybacked on this
        replica's heartbeat acks (None before the first checkpoint)."""
        with self._lock:
            if self.last is None:
                return None
            return {"index": self.last["index"],
                    "digest": self.last["digest"],
                    "per_table": self.last["per_table"]}

    # --------------------------------------------------------- quarantine

    def quarantine(self, reason: str) -> None:
        with self._lock:
            if self.quarantined:
                return
            self.quarantined = True
            self.quarantine_reason = reason
            self.counters["quarantines"] += 1
        global_metrics.incr("integrity.quarantine")
        global_metrics.set_gauge("integrity.quarantined", 1.0)
        log.warning("integrity: %s quarantined (%s) — stale/lease reads "
                    "refused until digest-verified re-admission",
                    self._node.name, reason)

    def clear_quarantine(self, why: str) -> None:
        with self._lock:
            if not self.quarantined:
                return
            self.quarantined = False
            self.quarantine_reason = ""
        global_metrics.set_gauge("integrity.quarantined", 0.0)
        log.warning("integrity: %s re-admitted (%s)", self._node.name, why)

    def verify_restore(self, expected: Optional[str]) -> Optional[bool]:
        """Digest-verified re-admission after a repair install: recompute
        the FULL digest of the restored store and compare against the
        digest the leader computed from the streamed blob.  Match clears
        quarantine; mismatch (the install path itself corrupted the
        bytes) stays quarantined so the leader retries; an absent
        expected digest (mixed-version leader) cannot verify."""
        tables = self._node.fsm.snapshot_tables()
        per = state_digest.tables_digests(tables)
        overall = state_digest.combine(per)
        with self._lock:
            self._cache = per
            self._dirty = {}
            self._all_dirty = False
            self.last = None        # no checkpoint since the rewind
        if expected is None:
            return None
        if overall == expected:
            self.clear_quarantine("repair digest verified")
            return True
        global_metrics.incr("integrity.repair_mismatch")
        log.warning("integrity: %s repair digest mismatch (want %s got "
                    "%s) — staying quarantined", self._node.name,
                    expected, overall)
        return False

    # ------------------------------------------------------ leader voting

    def observe_ack(self, peer: str, rep: Optional[dict]) -> None:
        """Record a follower's piggybacked digest report (None = the ack
        carried no digest field: a mixed-version peer, counted as
        unverified and never judged)."""
        with self._lock:
            if rep is None:
                self._unverified[peer] = self._unverified.get(peer, 0) + 1
                self.counters["unverified_acks"] += 1
            else:
                self._reports[peer] = rep
        if rep is None:
            global_metrics.incr("integrity.ack_unverified")

    def evaluate(self, voters, members=None) -> dict:
        """Majority-vote the newest checkpoint index.  Returns the
        actions the node must take: ``{"divergent": {peer: table},
        "self_outlier": bool, "repair": [peers]}``.  Quorum is over the
        VOTER set — non-voters are judged (and repaired) but never
        outvote the quorum.  `members` is the full replication set
        (voters + non-voters) when the caller knows it: convictions
        and reports for peers no longer in it are dropped — a destroyed
        server removed by membership change must not pin an
        unresolvable conviction (and an unhealthy verdict) forever."""
        actions = {"divergent": {}, "self_outlier": False, "repair": []}
        newly: Dict[str, str] = {}
        with self._lock:
            if members is not None:
                known = set(members)
                for gone in [p for p in self._divergent
                             if p not in known]:
                    del self._divergent[gone]
                for gone in [p for p in self._reports
                             if p not in known]:
                    del self._reports[gone]
            last = self.last
            if last is None:
                return actions
            idx = last["index"]
            me = self._node.name
            votes = {me: last}
            for peer, rep in self._reports.items():
                if rep.get("index") == idx:
                    votes[peer] = rep
            # clear divergence for peers whose current report agrees —
            # the self-heal path (repair landed, or a replaced server)
            for peer in list(self._divergent):
                rep = votes.get(peer)
                if rep is not None and rep["digest"] == last["digest"]:
                    del self._divergent[peer]
            digests = {rep["digest"] for rep in votes.values()}
            if len(digests) <= 1:
                actions["repair"] = sorted(self._divergent)
                return actions
            if idx > self._alarmed_index:
                self._alarmed_index = idx
                self.counters["alarms"] += 1
                global_metrics.incr("integrity.mismatch")
            if not last.get("full"):
                # incremental mismatch: alarm + escalate to a full walk;
                # conviction only ever happens on ground truth
                self._escalate.set()
                actions["repair"] = sorted(self._divergent)
                return actions
            # Judge fresh on EVERY ack at this index: reports trickle in
            # one heartbeat at a time, so the first pass at an index may
            # see too few same-index votes for any digest to reach
            # quorum — a later ack completes the vote.  Conviction is
            # idempotent through `_divergent`, so re-judging is free.
            need = len(set(voters)) // 2 + 1
            groups: Dict[str, list] = {}
            for name, rep in votes.items():
                groups.setdefault(rep["digest"], []).append(name)
            majority = None
            for dig, names in groups.items():
                if sum(1 for n in names if n in voters or n == me) >= need:
                    majority = dig
                    break
            if majority is None:
                # no digest reaches quorum yet (votes still in flight,
                # or too many unverified mixed-version peers): alarm
                # only, never quarantine
                actions["repair"] = sorted(self._divergent)
                return actions
            if last["digest"] != majority:
                actions["self_outlier"] = True
                return actions
            for name, rep in votes.items():
                if name == me or rep["digest"] == majority:
                    continue
                if name not in self._divergent:
                    self.counters["repairs_started"] += 1
                    table = state_digest.first_divergence(
                        last["per_table"], rep.get("per_table") or {})
                    self._divergent[name] = table or "?"
                    newly[name] = table or "?"
            actions["divergent"] = dict(self._divergent)
            actions["repair"] = sorted(self._divergent)
        for peer, table in sorted(newly.items()):
            global_metrics.incr("integrity.repair_start")
            log.warning(
                "integrity ALARM: replica %s diverged at checkpoint "
                "index %d — first divergent table %r; quarantining "
                "and starting anti-entropy repair", peer, idx, table)
        return actions

    def peer_divergent(self, peer: str) -> Optional[str]:
        """The first divergent table a convicted peer was convicted on
        (truthy while convicted), or None for a healthy peer."""
        with self._lock:
            return self._divergent.get(peer)

    def repair_result(self, peer: str, verified: Optional[bool]) -> None:
        """A repair stream finished for `peer`.  True = the follower
        digest-verified the restored state: conviction lifted.  False =
        verification failed (retry).  None = a mixed-version follower
        that cannot verify: lift the conviction and let the next
        checkpoint re-judge rather than repair-looping forever."""
        if verified is False:
            return
        with self._lock:
            was = self._divergent.pop(peer, None)
            # drop the pre-repair report too: it is stale by
            # construction (the repair rewound the peer past it) and
            # would instantly re-convict at the same checkpoint index
            self._reports.pop(peer, None)
            if was is not None and verified:
                self.counters["repairs_verified"] += 1
        if was is not None and verified:
            global_metrics.incr("integrity.repair_verified")
            log.warning("integrity: replica %s repaired and digest-"
                        "verified — re-admitted", peer)

    def escalation_pending(self) -> bool:
        return self._escalate.is_set()

    def take_escalation(self) -> bool:
        """Consume the escalate-to-full-walk request (proposer side)."""
        if self._escalate.is_set():
            self._escalate.clear()
            return True
        return False

    # ----------------------------------------------------- operator view

    def operator_view(self) -> dict:
        """The `/v1/operator/integrity` payload: this replica's local
        view (the leader's includes the per-peer report table)."""
        with self._lock:
            last = dict(self.last) if self.last else None
            if last is not None:
                last["per_table"] = dict(last["per_table"])
            peers = {}
            names = set(self._reports) | set(self._unverified)
            for peer in sorted(names):
                rep = self._reports.get(peer)
                peers[peer] = {
                    "index": rep["index"] if rep else None,
                    "digest": rep["digest"] if rep else None,
                    "lag": (last["index"] - rep["index"])
                    if (rep and last) else None,
                    "divergent": self._divergent.get(peer),
                    "unverified_acks": self._unverified.get(peer, 0),
                }
            return {
                "server": self._node.name,
                "quarantined": self.quarantined,
                "quarantine_reason": self.quarantine_reason,
                "last": last,
                "peers": peers,
                "counters": dict(self.counters),
            }
