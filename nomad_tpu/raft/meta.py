"""Durable Raft metadata: currentTerm + votedFor.

Raft requires both on stable storage BEFORE a node acts on them (Ongaro &
Ousterhout 2014, Figure 2: "updated on stable storage before responding
to RPCs").  A node that grants a vote, crashes, and forgets it can grant
a second vote in the same term — two leaders.  The reference gets this
from raft-boltdb's StableStore; this is the explicit equivalent.

The file is one small JSON object written write-temp → fsync → atomic
rename → directory fsync, so a crash at any instant leaves either the old
or the new metadata, never a torn mix.  It always fsyncs regardless of
``NOMAD_TPU_FSYNC`` — the file is tiny and written only on term/vote
changes, and surviving power loss is its entire purpose.  A failed fsync
raises `MetaPersistError`, and callers must then refuse the action that
needed durability (RaftNode refuses to grant the vote / abort the
candidacy).

A CRC over the body is stored alongside as belt-and-braces; rename
atomicity should make load-time corruption impossible, so a bad CRC or
unparseable file is treated as an operator problem (raise), not silently
reset — resetting would forget a vote, the exact bug this file prevents.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import zlib
from typing import Optional, Tuple

from nomad_tpu import chaos
from nomad_tpu.raft.log import fsync_dir

log = logging.getLogger(__name__)

META_VERSION = 1


class MetaPersistError(RuntimeError):
    """Term/vote could not be made durable (or loaded); the caller must
    not act as if it had been."""


def _encode_body(term: int, voted_for: Optional[str],
                 config: Optional[dict] = None) -> bytes:
    body = {"v": META_VERSION, "term": term, "voted_for": voted_for}
    if config is not None:
        # the config key joins the CRC body only when present, so files
        # written before dynamic membership still verify unchanged
        body["config"] = config
    return json.dumps(body, sort_keys=True).encode()


class DurableMeta:
    """Load-once, persist-on-change store for (term, voted_for)."""

    # wait-graph (nomad_tpu.analysis)
    _LOCK_BLOCKING_OK = {
        "_lock": "a term/vote update must be atomic with its fsync "
                 "(persist-before-respond), so the lock spans the write",
    }

    def __init__(self, path: str):
        self.path = path
        self.term = 0
        self.voted_for: Optional[str] = None
        # best-effort mirror of the latest cluster configuration
        # ({"voters": [...], "nonvoters": [...], "index": n}); the WAL and
        # snapshots are the durability anchors, this is a recovery belt
        self.config: Optional[dict] = None
        self._lock = threading.Lock()
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as fh:
                rec = json.loads(fh.read())
            body = _encode_body(int(rec["term"]), rec["voted_for"],
                                rec.get("config"))
            if int(rec["crc"]) != zlib.crc32(body):
                raise ValueError("crc mismatch")
            if int(rec["v"]) > META_VERSION:
                raise ValueError(f"meta version {rec['v']} newer than "
                                 f"supported {META_VERSION}")
            self.term = int(rec["term"])
            self.voted_for = rec["voted_for"]
            self.config = rec.get("config")
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError) as exc:
            # forgetting a persisted vote re-opens the double-vote window;
            # surface the damage instead of starting amnesiac
            raise MetaPersistError(
                f"raft metadata {self.path} unreadable ({exc}); refusing "
                f"to start with a possibly forgotten vote — restore or "
                f"remove the file deliberately") from exc

    def persist(self, term: int, voted_for: Optional[str]) -> None:
        """Durably record (term, voted_for); no-op when unchanged.
        Raises MetaPersistError if durability cannot be guaranteed."""
        with self._lock:
            if term == self.term and voted_for == self.voted_for:
                return
            self._write(term, voted_for, self.config)

    def persist_config(self, config: Optional[dict]) -> None:
        """Durably mirror the cluster configuration; no-op when unchanged.
        Shares the (term, voted_for) record and its write discipline."""
        with self._lock:
            if config == self.config:
                return
            self._write(self.term, self.voted_for, config)

    def _write(self, term: int, voted_for: Optional[str],
               config: Optional[dict]) -> None:
        """Write the full record durably (call under self._lock)."""
        rec = {"v": META_VERSION, "term": term, "voted_for": voted_for,
               "crc": zlib.crc32(_encode_body(term, voted_for, config))}
        if config is not None:
            rec["config"] = config
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".raft-meta-")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(json.dumps(rec, sort_keys=True).encode())
                fh.flush()
                if chaos.active is not None \
                        and chaos.should("disk.fsync_fail"):
                    raise OSError("chaos: injected fsync failure")
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise MetaPersistError(
                f"could not persist term/vote to {self.path}: {exc}"
            ) from exc
        fsync_dir(self.path)
        self.term = term
        self.voted_for = voted_for
        self.config = config

    def state(self) -> Tuple[int, Optional[str]]:
        with self._lock:
            return self.term, self.voted_for
