"""`python -m nomad_tpu` — the CLI entry point (reference: main.go)."""
import sys

from nomad_tpu.command import main

if __name__ == "__main__":
    sys.exit(main())
