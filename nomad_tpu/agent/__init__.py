"""Agent layer (reference: command/agent/ — the process that embeds a
server and/or client and serves the /v1 HTTP API)."""
from nomad_tpu.agent.agent import Agent, AgentConfig
from nomad_tpu.agent.http import HTTPServer

__all__ = ["Agent", "AgentConfig", "HTTPServer"]
