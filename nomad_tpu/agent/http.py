"""HTTP API server (reference: command/agent/http.go:320-392 — the /v1
route table over the agent's RPC layer).

Conventions mirrored from the reference:
 - JSON bodies both ways; struct wire format from nomad_tpu.api.codec.
 - Blocking queries: `?index=N&wait=SECONDS` on list/get endpoints —
   the handler waits until the state store advances past N (go-memdb
   watchsets in the reference; a condition poll here).
 - `X-Nomad-Index` response header carries the state index.
 - /v1/event/stream streams NDJSON events with topic filters.
 - ACL: `X-Nomad-Token` header resolved when ACLs are enabled.
"""
from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nomad_tpu import chaos, deadline, tracing
from nomad_tpu.api.codec import from_wire, to_wire
from nomad_tpu.raft.transport import Unreachable
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.serving import EventStreamer, READ_METHODS, mode_from_query
from nomad_tpu.structs import Job
from nomad_tpu.telemetry import global_metrics


class HTTPError(Exception):
    def __init__(self, code: int, msg: str,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.code = code
        self.msg = msg
        # overload refusals tell the client when to come back
        self.retry_after = retry_after


def _parse_wait(val: str) -> float:
    """`wait` accepts go-style durations ("5s", "100ms") or seconds."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h)?", val)
    if not m:
        raise HTTPError(400, f"invalid wait duration {val!r}")
    n = float(m.group(1))
    return n * {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
                None: 1.0}[m.group(2)]


class HTTPServer:
    def __init__(self, agent, host: str = "127.0.0.1", port: int = 0):
        self.agent = agent
        self.host = host
        # per-request read point (one handler thread per connection):
        # _rpc may only serve READ_METHODS from the local store when the
        # route gate established a read point for the CURRENT request
        self._read_local = threading.local()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):          # quiet
                pass

            def _dispatch(self):
                # set by _route: admission slot to hand back and the
                # previous deadline binding to restore (the connection
                # thread outlives the request under keep-alive)
                self._admitted = None
                self._deadline_bound = False
                self._deadline_prev = None
                try:
                    outer._route(self)
                except HTTPError as e:
                    self._reply(e.code, {"error": e.msg},
                                retry_after=e.retry_after)
                except RpcError as e:
                    code = {"not_found": 404, "permission_denied": 403,
                            "unknown_method": 404, "bad_request": 400,
                            "unknown_namespace": 400,
                            "unknown_region": 400,
                            "no_region_leader": 503,
                            "no_region_path": 502,
                            "admission_denied": 503,
                            "brownout": 503,
                            "quarantined": 503,
                            "deadline_exceeded": 504}.get(e.kind, 500)
                    self._reply(code, {"error": str(e)},
                                retry_after=getattr(e, "retry_after",
                                                    None))
                except Unreachable as e:
                    # a `?region=` request into a dark region fails fast
                    self._reply(503, {"error": f"region unreachable: {e}"})
                except BrokenPipeError:
                    pass
                except Exception as e:                   # noqa: BLE001
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                finally:
                    if self._admitted is not None:
                        gate, ns = self._admitted
                        gate.release(ns)
                    if self._deadline_bound:
                        deadline.bind(self._deadline_prev)

            do_GET = do_PUT = do_POST = do_DELETE = _dispatch

            def _reply(self, code: int, obj, index: Optional[int] = None,
                       ctx=None, retry_after: Optional[float] = None):
                body = json.dumps(obj).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    if retry_after is not None:
                        # overload refusal: an honest client hint
                        # (rounded up — Retry-After is integer seconds)
                        self.send_header(
                            "Retry-After",
                            str(max(1, int(retry_after + 0.999))))
                    if index is not None:
                        self.send_header("X-Nomad-Index", str(index))
                    if ctx is not None:
                        # staleness metadata from the read gate
                        # (reference setMeta, command/agent/http.go)
                        self.send_header(
                            "X-Nomad-KnownLeader",
                            "true" if ctx.known_leader else "false")
                        self.send_header(
                            "X-Nomad-LastContact",
                            str(int(ctx.last_contact_ms)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                if n == 0:
                    return {}
                raw = self.rfile.read(n)
                try:
                    return json.loads(raw) if raw else {}
                except json.JSONDecodeError as e:
                    raise HTTPError(400, f"invalid JSON body: {e}")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(2.0)

    # ------------------------------------------------------------ routing

    def _route(self, h) -> None:
        url = urllib.parse.urlparse(h.path)
        # keep_blank_values: bare flags like `?consistent` must survive
        q = {k: v[-1] for k, v in urllib.parse.parse_qs(
            url.query, keep_blank_values=True).items()}
        parts = [urllib.parse.unquote(p)
                 for p in url.path.split("/") if p]
        if not parts or parts[0] != "v1":
            raise HTTPError(404, f"no handler for {url.path}")
        parts = parts[1:]
        method = h.command

        # ---- overload plane, before any other work for the request
        # ingress-flood chaos: the front door sheds exactly as if this
        # tenant's bucket were empty — deny-by-503 with a Retry-After,
        # never accept-then-drop
        if chaos.active is not None and \
                chaos.should("overload.ingress_flood"):
            global_metrics.incr("admission.denied.flood")
            raise HTTPError(503, "ingress flood: request shed",
                            retry_after=1.0)
        ns = q.get("namespace", "default")
        gate = self.agent.server.admission \
            if self.agent.server is not None else None
        if gate is not None and gate.enabled:
            retry = gate.try_acquire(ns)
            if retry is not None:
                raise HTTPError(
                    503, f"admission limit for namespace {ns!r}",
                    retry_after=retry)
            h._admitted = (gate, ns)
        # request deadline: X-Nomad-Deadline carries the budget in
        # seconds (else the NOMAD_TPU_DEFAULT_DEADLINE default); bound
        # to the request thread so every downstream stage — rpc
        # dispatch, broker, applier, retry loops — checks it
        budget = h.headers.get("X-Nomad-Deadline")
        if budget is not None:
            try:
                budget = float(budget)
            except ValueError:
                raise HTTPError(
                    400, f"invalid X-Nomad-Deadline {budget!r}")
        else:
            budget = deadline.default_budget()
        if budget is not None:
            h._deadline_prev = deadline.bind(
                time.monotonic() + max(0.0, budget))
            h._deadline_bound = True

        token = h.headers.get("X-Nomad-Token", "") or \
            q.get("token", "")
        self._check_acl(parts, method, token, ns, h)

        server = self.agent.server
        store = server.store if server else None
        # `?region=`: a request for another region (reference
        # QueryOptions.Region) skips the LOCAL read gate — the remote
        # region's servers establish the read point — and instead rides
        # the consistency mode in the RPC args (see _rpc)
        region = q.get("region") or None
        if server is not None and region == server.region:
            region = None
        read_ctx = None
        if server is not None and method == "GET" and region is None:
            # establish the read point for this request's consistency
            # mode BEFORE any blocking wait: `?consistent` pays a quorum
            # round, default rides the leader lease, `?stale` serves
            # whatever the local store has right now
            mode = mode_from_query(q)
            gate_timeout = 2.0
            if "index" in q:
                # blocking queries bound the whole request by `wait`
                gate_timeout = min(_parse_wait(q.get("wait", "5s")), 600.0)
            try:
                read_ctx = server.serving_gate.begin_read(
                    mode, timeout=gate_timeout)
            except Exception as e:              # noqa: BLE001
                # vacant or unreachable leadership: linearizable reads
                # fail fast rather than serving possibly-stale data
                raise HTTPError(503, f"read gate ({mode}): "
                                     f"{type(e).__name__}: {e}")
        self._read_local.ctx = read_ctx
        self._read_local.region = region
        self._read_local.mode = mode_from_query(q) if region else None
        # local reads: the gate already ran above, but the brownout
        # shed decision inside endpoints.handle still needs the mode —
        # a stale read must shed LAST, not as a default read
        self._read_local.local_mode = mode_from_query(q) \
            if read_ctx is not None else None
        # trace ingress: one sampling decision per request; unsampled
        # requests (and a disabled tracer) skip everything below
        tracer = tracing.active
        tspan = tprev = None
        if tracer is not None and parts[0] != "traces":
            tctx = tracer.new_context()
            if tctx is not None:
                node = server.name if server is not None else "agent"
                tspan = tracer.start(
                    tctx, f"http.{method} /v1/{parts[0]}", node)
                tprev = tracing.bind(tracer.child_ctx(tctx, tspan))
        try:
            if store is not None and "index" in q and region is None:
                min_index = int(q["index"])
                wait = _parse_wait(q.get("wait", "5s"))
                # a deadline-bound blocking query parks for at most its
                # remaining budget, then serves the current state
                rem = deadline.remaining()
                if rem is not None:
                    wait = min(wait, rem)
                store.wait_for_index(min_index + 1, timeout=min(wait, 600.0))

            m = method.lower()
            candidates = []
            if len(parts) >= 2:
                candidates.append(f"_h_{m}_{parts[0]}_id")
            candidates.append(f"_h_{m}_{parts[0]}")
            handler = None
            for name in candidates:
                handler = getattr(self, name, None)
                if handler is not None:
                    break
            if handler is None:
                raise HTTPError(404, f"no handler for {method} {url.path}")
            result = handler(h, parts, q)
        finally:
            if tspan is not None:
                tracer.finish(tspan)
                tracing.bind(tprev)
            self._read_local.ctx = None
            self._read_local.region = None
            self._read_local.mode = None
            self._read_local.local_mode = None
        if result is not _STREAMED:
            # a cross-region reply must not carry the LOCAL store's
            # index as if it were the remote region's
            index = store.latest_index \
                if store is not None and region is None else None
            if index is not None and "index" in q:
                # a blocking query must never return an index lower than
                # the one it was given (reference blockingRPC contract)
                index = max(index, int(q["index"]))
            h._reply(200, to_wire(result), index=index, ctx=read_ctx)

    def _rpc(self, method: str, args: dict):
        server = self.agent.server
        if tracing.active is not None:
            ctx = tracing.current()
            if ctx is not None:
                # sampled request: the context rides the RPC args
                # (endpoints.handle pops it before dispatch; forwarded
                # copies keep it, so it survives federation hops)
                args = dict(args)
                args[tracing.TRACE_KEY] = ctx
        if deadline.current() is not None:
            # the request's remaining budget rides the RPC args just
            # like the trace ctx, re-encoded relative so clock skew
            # between hops cannot spuriously expire it
            args = dict(args)
            args[deadline.DEADLINE_KEY] = deadline.to_wire()
        region = getattr(self._read_local, "region", None)
        if server is not None and region:
            # cross-region request: ship the target region (and the
            # caller's consistency mode, applied by the REMOTE region's
            # read gate) in the args — endpoints.handle forwards it over
            # the WAN to that region's current leader
            args = dict(args)
            args["region"] = region
            mode = getattr(self._read_local, "mode", None)
            if mode is not None and method in READ_METHODS:
                args["consistency"] = mode
            return server.endpoints.handle(method, args)
        if server is not None and method in READ_METHODS \
                and getattr(self._read_local, "ctx", None) is not None:
            # a read point was established by _route's gate for THIS
            # request: serve from the LOCAL store, leader and follower
            # alike (follower reads).  Reads invoked without one — e.g.
            # from POST paths like /v1/search or job evaluate/revert
            # preconditions — forward to the leader as before, rather
            # than reading an ungated follower store with no staleness
            # metadata.
            local_mode = getattr(self._read_local, "local_mode", None)
            if local_mode is not None:
                # ride the args for shed classification only — the read
                # point for this request is already established, so it
                # must NOT trigger a second begin_read
                args = dict(args)
                args["_read_mode"] = local_mode
            return server.endpoints.handle(method, args)
        return self.agent.rpc(method, args)

    # ------------------------------------------------------------ ACL

    def _check_acl(self, parts, method, token: str,
                   namespace: str = "default", h=None) -> None:
        server = self.agent.server
        if server is None or not getattr(server, "acl_enabled", False):
            if h is not None:
                h.acl = None
            return
        from nomad_tpu.acl import required_capability
        cap, ns = required_capability(parts, method, namespace)
        if cap is None:
            if h is not None:
                h.acl = server.resolve_token(token)
            return
        acl = server.resolve_token(token)
        if h is not None:
            h.acl = acl
        if acl is None:
            raise HTTPError(403, "ACL token not found")
        if not acl.allows(ns, cap):
            raise HTTPError(403, f"Permission denied: needs {cap}")

    def _require_ns_cap(self, h, namespace: str, cap: str) -> None:
        """Authorize `cap` against the *object's own* namespace after
        fetching it by ID (the reference checks alloc.Namespace in
        alloc_endpoint.go, not the caller-supplied ?namespace= param —
        otherwise a token with the capability in any one namespace could
        act on objects in all of them)."""
        if not getattr(self.agent.server, "acl_enabled", False):
            return
        acl = getattr(h, "acl", None)
        if acl is None or not acl.allows(namespace, cap):
            raise HTTPError(403, f"Permission denied: needs {cap} in "
                                 f"namespace {namespace!r}")

    def _require_ns_read(self, h, namespace: str) -> None:
        from nomad_tpu.acl.policy import CAP_READ_JOB
        self._require_ns_cap(h, namespace, CAP_READ_JOB)

    def _ns_visible(self, h, namespace: str) -> bool:
        """Namespace-level read filter for list endpoints (the reference
        scopes every list RPC by the token's namespace grants)."""
        if not getattr(self.agent.server, "acl_enabled", False):
            return True
        acl = getattr(h, "acl", None)
        if acl is None:
            return False
        from nomad_tpu.acl.policy import CAP_LIST_JOBS, CAP_READ_JOB
        return acl.allows(namespace, CAP_LIST_JOBS) or \
            acl.allows(namespace, CAP_READ_JOB)

    def _ns_param(self, q):
        """Validate `?namespace=`: an unknown namespace is rejected
        naming the known set (matching Job.Register's unknown-region
        error shape); `*` is the wildcard list-all.  Cross-region
        requests skip the check — only the remote region knows its
        namespaces."""
        ns = q.get("namespace")
        if not ns or ns == "*":
            return ns
        server = self.agent.server
        if server is None or getattr(self._read_local, "region", None):
            return ns
        if server.store.namespace(ns) is None:
            known = sorted(n.name for n in server.store.namespaces())
            raise HTTPError(
                400, f"unknown namespace {ns!r} (known namespaces: "
                     f"{', '.join(known)})")
        return ns

    # ------------------------------------------------------------ jobs

    def _h_get_jobs(self, h, parts, q):
        jobs = self._rpc("Job.List", {"namespace": self._ns_param(q)})
        prefix = q.get("prefix", "")
        return [_job_stub(j) for j in jobs
                if j.id.startswith(prefix)
                and self._ns_visible(h, j.namespace)]

    def _h_put_jobs(self, h, parts, q):
        body = h._body()
        if len(parts) > 1 and parts[1] == "parse":
            return self._parse_jobspec(body)
        job = from_wire(Job, body.get("Job") or body.get("job") or body)
        # the authoritative namespace is the one in the job body — re-check
        # against it (the URL-level check used the ?namespace= param)
        acl = getattr(h, "acl", None)
        if getattr(self.agent.server, "acl_enabled", False):
            from nomad_tpu.acl.policy import CAP_SUBMIT_JOB
            if acl is None or not acl.allows(job.namespace, CAP_SUBMIT_JOB):
                raise HTTPError(
                    403, f"Permission denied: needs submit-job in "
                         f"namespace {job.namespace!r}")
        resp = self._rpc("Job.Register", {"job": job})
        return {"EvalID": resp["eval_id"],
                "JobModifyIndex": resp["job_modify_index"]}

    _h_post_jobs = _h_put_jobs

    def _parse_jobspec(self, body):
        from nomad_tpu.jobspec import parse_job
        src = body.get("JobHCL") or body.get("job_hcl") or ""
        if not src:
            raise HTTPError(400, "JobHCL required")
        return parse_job(src)

    # sub-resources under /v1/job/<id>/... ; the id itself may contain
    # slashes (dispatched/periodic children), so scan from the end
    _JOB_SUBS = {"allocations", "evaluations", "deployments", "deployment",
                 "summary", "versions", "evaluate", "plan", "dispatch",
                 "stability", "revert", "force", "scale"}

    @classmethod
    def _job_path(cls, parts):
        """['job', *id-segments, sub?] -> (job_id, sub)."""
        segs = parts[1:]
        if segs and segs[-1] == "force" and len(segs) >= 2 \
                and segs[-2] == "periodic":
            return "/".join(segs[:-2]), "periodic/force"
        if segs and segs[-1] in cls._JOB_SUBS:
            return "/".join(segs[:-1]), segs[-1]
        return "/".join(segs), None

    def _h_get_job_id(self, h, parts, q):
        ns = q.get("namespace", "default")
        job_id, sub = self._job_path(parts)
        store = self.agent.server.store
        if sub is None:
            job = self._rpc("Job.GetJob", {"namespace": ns, "job_id": job_id})
            if job is None:
                raise HTTPError(404, f"job not found: {job_id}")
            return job
        if sub == "allocations":
            return [_alloc_stub(a) for a in self._rpc(
                "Job.Allocations", {"namespace": ns, "job_id": job_id})]
        if sub == "evaluations":
            return self._rpc("Job.Evaluations",
                             {"namespace": ns, "job_id": job_id})
        if sub == "deployments":
            return [d for d in self._rpc("Deployment.List", {})
                    if d.job_id == job_id and d.namespace == ns]
        if sub == "deployment":
            return store.latest_deployment_by_job_id(ns, job_id)
        if sub == "summary":
            return store.job_summary(ns, job_id)
        if sub == "versions":
            return store.job_versions(ns, job_id)
        if sub == "scale":
            return self._rpc("Job.ScaleStatus",
                             {"namespace": ns, "job_id": job_id})
        raise HTTPError(404, f"no handler for job/{sub}")

    def _h_put_job_id(self, h, parts, q):
        ns = q.get("namespace", "default")
        job_id, sub = self._job_path(parts)
        if sub is None:                      # update = register
            return self._h_put_jobs(h, ["jobs"], q)
        if sub == "scale":
            body = h._body()
            target = body.get("Target", {}) or {}
            return self._rpc("Job.Scale", {
                "namespace": ns, "job_id": job_id,
                "group": target.get("Group", body.get("group", "")),
                "count": body.get("Count", body.get("count")),
                "message": body.get("Message", ""),
                "error": bool(body.get("Error", False)),
                "meta": body.get("Meta")})
        if sub == "evaluate":
            job = self._rpc("Job.GetJob", {"namespace": ns, "job_id": job_id})
            if job is None:
                raise HTTPError(404, f"job not found: {job_id}")
            from nomad_tpu.structs import Evaluation, EvalStatus
            from nomad_tpu.structs.evaluation import EvalTrigger
            ev = Evaluation(namespace=ns, priority=job.priority,
                            type=job.type, job_id=job_id,
                            triggered_by=EvalTrigger.JOB_REGISTER,
                            status=EvalStatus.PENDING)
            self._rpc("Eval.Create", {"evals": [ev]})
            return {"EvalID": ev.id}
        if sub == "plan":
            body = h._body()
            job = from_wire(Job, body.get("Job") or body.get("job") or {})
            return self._rpc("Job.Plan", {"job": job,
                                          "diff": body.get("Diff", True)})
        if sub == "periodic/force":
            return self._force_periodic(ns, job_id)
        if sub == "dispatch":
            body = h._body()
            return self._rpc("Job.Dispatch", {
                "namespace": ns, "job_id": job_id,
                "payload": body.get("Payload", ""),
                "meta": body.get("Meta") or {}})
        if sub == "stability":
            body = h._body()
            self._rpc("Job.Stability", {
                "namespace": ns, "job_id": job_id,
                "version": body.get("JobVersion", 0),
                "stable": body.get("Stable", True)})
            return {}
        if sub == "revert":
            body = h._body()
            return self._rpc("Job.Revert", {
                "namespace": ns, "job_id": job_id,
                "version": body.get("JobVersion", 0)})
        raise HTTPError(404, f"no handler for job/{sub}")

    _h_post_job_id = _h_put_job_id

    def _force_periodic(self, ns, job_id):
        server = self.agent.server
        job = server.store.job_by_id(ns, job_id)
        if job is None or not job.is_periodic():
            raise HTTPError(404, f"periodic job not found: {job_id}")
        child_id = server.periodic._launch(job, time.time())
        return {"DispatchedJobID": child_id}

    def _h_delete_job_id(self, h, parts, q):
        job_id, _ = self._job_path(parts)
        resp = self._rpc("Job.Deregister", {
            "namespace": q.get("namespace", "default"), "job_id": job_id,
            "purge": q.get("purge", "").lower() == "true"})
        return {"EvalID": resp["eval_id"]}

    # ------------------------------------------------------------ nodes

    def _h_get_nodes(self, h, parts, q):
        prefix = q.get("prefix", "")
        return [_node_stub(n) for n in self._rpc("Node.List", {})
                if n.id.startswith(prefix)]

    def _h_get_node_id(self, h, parts, q):
        sub = parts[2] if len(parts) > 2 else None
        if sub == "allocations":
            return self._rpc("Node.GetAllocs", {"node_id": parts[1]})
        node = self._rpc("Node.GetNode", {"node_id": parts[1]})
        if node is None:
            raise HTTPError(404, f"node not found: {parts[1]}")
        return node

    def _h_put_node_id(self, h, parts, q):
        sub = parts[2] if len(parts) > 2 else None
        body = h._body()
        if sub == "drain":
            spec = body.get("DrainSpec")
            if spec:
                self._rpc("Node.UpdateDrain", {
                    "node_id": parts[1],
                    "deadline_s": float(spec.get("Deadline", 3600.0)),
                    "ignore_system_jobs": spec.get("IgnoreSystemJobs",
                                                   False)})
            else:                      # nil spec = cancel (reference API)
                self._rpc("Node.CancelDrain", {"node_id": parts[1]})
            return {}
        if sub == "eligibility":
            self._rpc("Node.UpdateEligibility", {
                "node_id": parts[1],
                "eligibility": body.get("Eligibility", "eligible")})
            return {}
        if sub == "purge":
            self._rpc("Node.Deregister", {"node_id": parts[1]})
            return {}
        raise HTTPError(404, f"no handler for node/{sub}")

    _h_post_node_id = _h_put_node_id

    # ------------------------------------------------------------ evals/allocs

    def _h_get_evaluations(self, h, parts, q):
        prefix = q.get("prefix", "")
        return [e for e in self._rpc("Eval.List",
                                     {"namespace": self._ns_param(q)})
                if e.id.startswith(prefix)
                and self._ns_visible(h, e.namespace)]

    def _h_get_evaluation_id(self, h, parts, q):
        sub = parts[2] if len(parts) > 2 else None
        if sub == "allocations":
            allocs = [a for a in self._rpc("Alloc.List", {})
                      if a.eval_id == parts[1]]
            for a in allocs:
                self._require_ns_read(h, a.namespace)
            return allocs
        ev = self._rpc("Eval.GetEval", {"eval_id": parts[1]})
        if ev is None:
            raise HTTPError(404, f"eval not found: {parts[1]}")
        self._require_ns_read(h, ev.namespace)
        return ev

    def _h_get_allocations(self, h, parts, q):
        prefix = q.get("prefix", "")
        return [_alloc_stub(a) for a in
                self._rpc("Alloc.List", {"namespace": self._ns_param(q)})
                if a.id.startswith(prefix)
                and self._ns_visible(h, a.namespace)]

    def _h_get_allocation_id(self, h, parts, q):
        a = self._rpc("Alloc.GetAlloc", {"alloc_id": parts[1]})
        if a is None:
            raise HTTPError(404, f"alloc not found: {parts[1]}")
        self._require_ns_read(h, a.namespace)
        return a

    def _h_post_allocation_id(self, h, parts, q):
        sub = parts[2] if len(parts) > 2 else None
        if sub == "stop":
            a = self._rpc("Alloc.GetAlloc", {"alloc_id": parts[1]})
            if a is None:
                raise HTTPError(404, f"alloc not found: {parts[1]}")
            from nomad_tpu.acl.policy import CAP_ALLOC_LIFECYCLE
            self._require_ns_cap(h, a.namespace, CAP_ALLOC_LIFECYCLE)
            return self._rpc("Alloc.Stop", {"alloc_id": parts[1]})
        raise HTTPError(404, f"no handler for allocation/{sub}")

    _h_put_allocation_id = _h_post_allocation_id

    # ------------------------------------------------------------ deployments

    def _h_get_deployments(self, h, parts, q):
        return [d for d in
                self._rpc("Deployment.List",
                          {"namespace": self._ns_param(q)})
                if self._ns_visible(h, d.namespace)]

    def _h_get_deployment_id(self, h, parts, q):
        d = self._rpc("Deployment.GetDeployment",
                      {"deployment_id": parts[1]})
        if d is None:
            raise HTTPError(404, f"deployment not found: {parts[1]}")
        self._require_ns_read(h, d.namespace)
        return d

    def _h_put_deployment_id(self, h, parts, q):
        # /v1/deployment/<verb>/<id> (reference routing)
        verb, dep_id = parts[1], parts[2] if len(parts) > 2 else None
        body = h._body()
        if verb == "promote":
            return self._rpc("Deployment.Promote", {
                "deployment_id": dep_id, "groups": body.get("Groups")})
        if verb == "fail":
            return self._rpc("Deployment.Fail", {"deployment_id": dep_id})
        if verb == "pause":
            return self._rpc("Deployment.Pause", {
                "deployment_id": dep_id, "pause": body.get("Pause", True)})
        raise HTTPError(404, f"no handler for deployment/{verb}")

    _h_post_deployment_id = _h_put_deployment_id

    # ------------------------------------------------------------ operator

    def _h_get_operator(self, h, parts, q):
        if parts[1:3] == ["scheduler", "configuration"]:
            cfg = self._rpc("Operator.SchedulerGetConfiguration", {})
            return {"SchedulerConfig": cfg}
        if parts[1:3] == ["raft", "configuration"]:
            cfg = self._rpc("Operator.RaftGetConfiguration", {})
            return {
                "Index": cfg["index"],
                "Servers": [
                    {"ID": n, "Node": n, "Voter": True,
                     "Leader": n == cfg["leader"]}
                    for n in cfg["voters"]
                ] + [
                    {"ID": n, "Node": n, "Voter": False, "Leader": False}
                    for n in cfg["nonvoters"]
                ],
            }
        if parts[1:2] == ["integrity"]:
            # local replica's integrity view: last checkpoint digest,
            # quarantine state, repair counters (leader adds per-peer
            # report table)
            return self._rpc("Operator.Integrity", {})
        raise HTTPError(404, "unknown operator path")

    def _h_put_operator(self, h, parts, q):
        if parts[1:3] == ["scheduler", "configuration"]:
            from nomad_tpu.structs.config import SchedulerConfiguration
            cfg = from_wire(SchedulerConfiguration, h._body())
            self._rpc("Operator.SchedulerSetConfiguration", {"config": cfg})
            return {"Updated": True}
        if parts[1:3] == ["raft", "remove-peer"]:
            body = h._body() or {}
            name = body.get("ID") or body.get("Node") or q.get("id", "")
            if not name:
                raise HTTPError(400, "missing peer id")
            out = self._rpc("Operator.RaftRemovePeer", {"name": name})
            return {"Index": out["index"]}
        if parts[1:3] == ["raft", "transfer-leadership"]:
            body = h._body() or {}
            out = self._rpc("Operator.TransferLeadership",
                            {"name": body.get("ID") or body.get("Node")})
            return {"Transferred": out["transferred"],
                    "Leader": out["leader"]}
        raise HTTPError(404, "unknown operator path")

    _h_post_operator = _h_put_operator

    # ------------------------------------------------------------ status/agent

    def _h_get_status(self, h, parts, q):
        if parts[1] == "leader":
            return self._rpc("Status.Leader", {})
        if parts[1] == "peers":
            return self._rpc("Status.Peers", {})
        raise HTTPError(404, "unknown status path")

    # ------------------------------------------------------------ client fs

    def _h_get_client_id(self, h, parts, q):
        """/v1/client/fs/{ls,stat,cat,logs}/<alloc_id> — alloc filesystem
        and task log access (reference client/fs_endpoint.go +
        command/agent/fs_endpoint.go).  Requests for allocs on another
        node forward to that node's advertised agent address (the
        reference's server->client streaming hop)."""
        import os

        if len(parts) < 4 or parts[1] != "fs":
            raise HTTPError(404, "expected /v1/client/fs/<verb>/<alloc>")
        verb, alloc_id = parts[2], parts[3]
        # re-check the capability against the alloc's OWN namespace when
        # this agent can see the record (the ?namespace= param is only
        # the caller's claim, same discipline as the alloc endpoints)
        if self.agent.server is not None:
            alloc = self.agent.server.store.alloc_by_id(alloc_id)
            if alloc is not None:
                from nomad_tpu.acl.policy import CAP_READ_FS, CAP_READ_LOGS
                self._require_ns_cap(
                    h, alloc.namespace,
                    CAP_READ_LOGS if verb == "logs" else CAP_READ_FS)
        client = self.agent.client
        root = None
        if client is not None:
            cand = os.path.join(client.alloc_dir_root, alloc_id)
            if os.path.isdir(cand):
                root = cand
        if root is None:
            # one forwarding hop only: a forwarded request that still
            # finds no local dir must 404, not bounce again (self-proxy
            # loop when a combined agent's alloc dir is already gone)
            if h.headers.get("X-Nomad-Forwarded"):
                raise HTTPError(404,
                                f"allocation {alloc_id} not on this node")
            return self._proxy_fs(h, parts, q)

        def resolve(rel: str) -> str:
            p = os.path.realpath(os.path.join(root, rel.lstrip("/")))
            real_root = os.path.realpath(root)
            if not (p + os.sep).startswith(real_root + os.sep) \
                    and p != real_root:
                raise HTTPError(403, "path escapes allocation directory")
            # secrets dirs are invisible to the fs API even inside the
            # alloc dir (reference client/allocdir escapingfs + the
            # secrets-dir guard, fs_endpoint.go): layout is
            # <alloc>/<task>/secrets — reject any resolved path whose
            # second component under the alloc root is "secrets"
            rel_parts = os.path.relpath(p, real_root).split(os.sep)
            if len(rel_parts) >= 2 and rel_parts[1] == "secrets":
                raise HTTPError(403, "path is in a secrets directory")
            return p

        if verb == "ls":
            d = resolve(q.get("path", "/"))
            if not os.path.isdir(d):
                raise HTTPError(404, f"not a directory: {q.get('path')}")
            out = []
            for name in sorted(os.listdir(d)):
                try:
                    st = os.lstat(os.path.join(d, name))
                except OSError:
                    continue       # raced deletion / dangling symlink
                out.append({"Name": name,
                            "IsDir": os.path.isdir(os.path.join(d, name)),
                            "Size": st.st_size, "ModTime": st.st_mtime})
            return out
        if verb == "stat":
            p = resolve(q.get("path", "/"))
            if not os.path.exists(p):
                raise HTTPError(404, f"no such file: {q.get('path')}")
            st = os.stat(p)
            return {"Name": os.path.basename(p), "IsDir": os.path.isdir(p),
                    "Size": st.st_size, "ModTime": st.st_mtime}
        if verb == "cat":
            p = resolve(q.get("path", "/"))
            if not os.path.isfile(p):
                raise HTTPError(404, f"no such file: {q.get('path')}")
            with open(p, "rb") as fh:
                data = fh.read()
            return self._raw_reply(h, data)
        if verb == "logs":
            return self._client_logs(h, q, root)
        raise HTTPError(404, f"unknown fs verb {verb!r}")

    @staticmethod
    def _raw_reply(h, data: bytes):
        h.send_response(200)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        try:
            h.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass
        return _STREAMED

    def _client_logs(self, h, q, root: str):
        """?task=&type=stdout|stderr&offset=&origin=start|end&follow="""
        import os

        from nomad_tpu.client.logmon import log_size, read_log
        task = q.get("task", "")
        kind = q.get("type", "stdout")
        if kind not in ("stdout", "stderr"):
            raise HTTPError(400, "type must be stdout or stderr")
        logs_dir = os.path.join(root, "alloc", "logs")
        offset = int(q.get("offset", 0))
        if q.get("origin", "start") == "end":
            offset = max(0, log_size(logs_dir, task, kind) - offset)
        if q.get("follow", "") not in ("true", "1"):
            data, _ = read_log(logs_dir, task, kind, offset)
            return self._raw_reply(h, data)
        # follow: chunked stream of appended bytes until timeout/close
        deadline = time.time() + float(q.get("timeout", 30.0))
        h.send_response(200)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()
        try:
            while time.time() < deadline:
                data, offset = read_log(logs_dir, task, kind, offset)
                if data:
                    h.wfile.write(hex(len(data))[2:].encode() + b"\r\n"
                                  + data + b"\r\n")
                    h.wfile.flush()
                else:
                    time.sleep(0.25)
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        return _STREAMED

    def _proxy_fs(self, h, parts, q):
        """Forward an fs request to the agent on the alloc's node."""
        import urllib.request

        server = self.agent.server
        if server is None:
            raise HTTPError(404, "allocation not on this node")
        alloc = server.store.alloc_by_id(parts[3])
        if alloc is None:
            raise HTTPError(404, f"unknown allocation {parts[3]}")
        node = server.store.node_by_id(alloc.node_id)
        addr = getattr(node, "http_addr", "") if node else ""
        if not addr:
            raise HTTPError(
                404, "allocation's node advertises no HTTP address")
        url = (f"http://{addr}/v1/" + "/".join(parts)
               + ("?" + urllib.parse.urlencode(q) if q else ""))
        headers = {"X-Nomad-Forwarded": "1"}
        token = h.headers.get("X-Nomad-Token", "")
        if token:
            headers["X-Nomad-Token"] = token   # ACLs check on both hops
        req = urllib.request.Request(url, headers=headers)
        # socket timeout must outlast a quiet follow window, or an idle
        # tail-follow is silently truncated mid-stream
        timeout = float(q.get("timeout", 30.0)) + 30.0
        # connect BEFORE writing any response bytes: upstream errors
        # must map to clean statuses, not corrupt a half-sent stream
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            raise HTTPError(e.code, e.read().decode(errors="replace"))
        except Exception as e:                       # noqa: BLE001
            raise HTTPError(502, f"fs forward to {addr} failed: {e}")
        try:
            with resp:
                h.send_response(resp.status)
                h.send_header("Content-Type",
                              resp.headers.get("Content-Type",
                                               "application/octet-stream"))
                h.send_header("Transfer-Encoding", "chunked")
                h.end_headers()
                while True:
                    chunk = resp.read(65536)
                    if not chunk:
                        break
                    h.wfile.write(hex(len(chunk))[2:].encode() + b"\r\n"
                                  + chunk + b"\r\n")
                    h.wfile.flush()
                h.wfile.write(b"0\r\n\r\n")
        except Exception:                            # noqa: BLE001
            # headers already sent: truncate the stream, never write a
            # second status line into it
            pass
        return _STREAMED

    def _h_get_agent(self, h, parts, q):
        if parts[1] == "self":
            cfg = self.agent.config
            return {"config": to_wire(cfg), "member": {"Name": cfg.name},
                    "stats": {"client": self.agent.client is not None,
                              "server": self.agent.server is not None}}
        if parts[1] == "members":
            return {"Members": [
                {"Name": m["name"], "Status": m["status"],
                 "Addr": m["addr"]}
                for m in self._rpc("Status.Members", {})]}
        if parts[1] == "health":
            return {"server": {"ok": self.agent.server is not None},
                    "client": {"ok": self.agent.client is not None}}
        if parts[1] == "pprof":
            return self._agent_pprof(h, parts, q)
        if parts[1] == "monitor":
            return self._agent_monitor(h, q)
        raise HTTPError(404, "unknown agent path")

    def _agent_pprof(self, h, parts, q):
        """/v1/agent/pprof/profile — CPU profile of this agent for
        ?seconds= (cProfile stats text; the Python analog of the pprof
        protobuf the reference serves, command/agent/http.go:379-381).
        /v1/agent/pprof/goroutine — all-thread stack dump."""
        kind = parts[2] if len(parts) > 2 else "profile"
        if kind in ("goroutine", "threads"):
            import sys
            import threading as _threading
            import traceback
            names = {t.ident: t.name for t in _threading.enumerate()}
            out = []
            for tid, frame in sys._current_frames().items():
                out.append(f"Thread {names.get(tid, tid)}:\n"
                           + "".join(traceback.format_stack(frame)))
            return {"stacks": "\n".join(out)}
        if kind != "profile":
            raise HTTPError(404, f"unknown pprof kind {kind}")
        import cProfile
        import io
        import pstats
        seconds = min(float(q.get("seconds", 1.0)), 30.0)
        prof = cProfile.Profile()
        prof.enable()
        time.sleep(seconds)
        prof.disable()
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
            .print_stats(60)
        return {"seconds": seconds, "profile": buf.getvalue()}

    def _agent_monitor(self, h, q):
        """/v1/agent/monitor — chunked stream of this agent's log lines
        (reference command/agent/agent_endpoint.go monitor)."""
        deadline = time.time() + float(q.get("timeout", 5.0))
        last_seq = 0
        h.send_response(200)
        h.send_header("Content-Type", "text/plain")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()
        try:
            # replay the ring, then follow by sequence number (the ring
            # rotates; indexes would shift under the reader)
            while time.time() < deadline:
                snap = [(seq, line) for seq, line
                        in list(self.agent.log_ring) if seq > last_seq]
                new = [line for _, line in snap]
                if new:
                    last_seq = snap[-1][0]
                    chunk = ("\n".join(new) + "\n").encode()
                    h.wfile.write(hex(len(chunk))[2:].encode() + b"\r\n"
                                  + chunk + b"\r\n")
                    h.wfile.flush()
                else:
                    with self.agent._log_cv:
                        self.agent._log_cv.wait(0.25)
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        return _STREAMED

    # ------------------------------------------------------------ search

    def _h_post_search(self, h, parts, q):
        """Prefix search via the server-side Search.PrefixSearch RPC
        (reference nomad/search_endpoint.go); the agent only computes the
        caller's namespace visibility from its ACL token."""
        body = h._body()
        namespaces = None
        if getattr(self.agent.server, "acl_enabled", False):
            store = self.agent.server.store
            namespaces = [ns.name for ns in store.namespaces()
                          if self._ns_visible(h, ns.name)]
        resp = self._rpc("Search.PrefixSearch", {
            "prefix": body.get("Prefix", ""),
            "context": body.get("Context", "all"),
            "namespaces": namespaces})
        return {"Matches": resp["matches"],
                "Truncations": resp["truncations"]}

    # ------------------------------------------------------------ metrics

    def _h_get_metrics(self, h, parts, q):
        if q.get("format") == "prometheus":
            body = global_metrics.prometheus().encode()
            h.send_response(200)
            h.send_header("Content-Type", "text/plain; version=0.0.4")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
            return _STREAMED
        return global_metrics.snapshot()

    # ------------------------------------------------------------ traces

    def _h_get_traces(self, h, parts, q):
        """/v1/traces — trace summaries from the in-process span stores;
        /v1/traces/<trace_id> — that trace's spans (`?format=chrome`
        exports Chrome-trace JSON for Perfetto)."""
        tracer = tracing.active
        if tracer is None:
            raise HTTPError(404, "tracing disabled "
                                 "(set NOMAD_TPU_TRACE=1)")
        return tracer.traces()

    def _h_get_traces_id(self, h, parts, q):
        tracer = tracing.active
        if tracer is None:
            raise HTTPError(404, "tracing disabled "
                                 "(set NOMAD_TPU_TRACE=1)")
        trace_id = parts[1]
        spans = [s.to_dict() for s in tracer.spans(trace_id)]
        if not spans:
            raise HTTPError(404, f"no spans for trace {trace_id!r}")
        if q.get("format") == "chrome":
            return tracing.chrome_trace(spans)
        return {"trace_id": trace_id, "spans": spans}

    # ------------------------------------------------------------ events

    def _h_get_event(self, h, parts, q):
        """/v1/event/stream — NDJSON event stream with ?topic=Topic:Key
        filters (reference nomad/stream/ndjson.go)."""
        if len(parts) < 2 or parts[1] != "stream":
            raise HTTPError(404, "unknown event path")
        topics: dict = {}
        raw = urllib.parse.urlparse(h.path).query
        for k, vals in urllib.parse.parse_qs(raw).items():
            if k != "topic":
                continue
            for v in vals:
                topic, _, key = v.partition(":")
                topics.setdefault(topic, []).append(key or "*")
        if not topics:
            topics = {"*": ["*"]}
        acl_on = getattr(self.agent.server, "acl_enabled", False)
        sub = self.agent.server.event_broker.subscribe(
            topics, from_index=int(q.get("index", 0)))
        filter_fn = None
        if acl_on:
            filter_fn = (lambda ev: not ev.namespace
                         or self._ns_visible(h, ev.namespace))
        heartbeat = _parse_wait(q["heartbeat"]) if "heartbeat" in q else None
        streamer = EventStreamer(sub, heartbeat=heartbeat,
                                 filter_fn=filter_fn)
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def write(chunk: bytes) -> None:
                h.wfile.write(hex(len(chunk))[2:].encode() + b"\r\n"
                              + chunk + b"\r\n")
                h.wfile.flush()

            streamer.run(write, float(q.get("timeout", 5.0)))
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            sub.close()
        return _STREAMED

    # ------------------------------------------------------------ ACL mgmt

    def _h_get_acl(self, h, parts, q):
        server = self.agent.server
        if parts[1] == "policies":
            return [{"Name": p.name, "Description": p.description}
                    for p in server.acl_policies()]
        if parts[1] == "policy" and len(parts) > 2:
            p = server.acl_policy(parts[2])
            if p is None:
                raise HTTPError(404, f"policy not found: {parts[2]}")
            return {"Name": p.name, "Description": p.description,
                    "Rules": p.rules}
        if parts[1] == "tokens":
            return [_token_stub(t) for t in server.acl_tokens()]
        if parts[1] == "token" and len(parts) > 2:
            t = server.acl_token(parts[2]) if parts[2] != "self" else \
                server.acl_token_by_secret(
                    h.headers.get("X-Nomad-Token", ""))
            if t is None:
                raise HTTPError(404, "token not found")
            return _token_full(t)
        raise HTTPError(404, "unknown acl path")

    def _h_put_acl(self, h, parts, q):
        server = self.agent.server
        body = h._body()
        if parts[1] == "policy" and len(parts) > 2:
            server.upsert_acl_policy(
                parts[2], body.get("Description", ""),
                body.get("Rules", ""))
            return {}
        if parts[1] == "token":
            t = server.create_acl_token(
                name=body.get("Name", ""),
                type_=body.get("Type", "client"),
                policies=body.get("Policies") or [])
            return _token_full(t)
        if parts[1] == "bootstrap":
            t = server.bootstrap_acl()
            return _token_full(t)
        raise HTTPError(404, "unknown acl path")

    _h_post_acl = _h_put_acl

    def _h_delete_acl(self, h, parts, q):
        server = self.agent.server
        if parts[1] == "policy" and len(parts) > 2:
            server.delete_acl_policy(parts[2])
            return {}
        if parts[1] == "token" and len(parts) > 2:
            server.delete_acl_token(parts[2])
            return {}
        raise HTTPError(404, "unknown acl path")

    # ------------------------------------------------------------ namespaces

    def _h_get_namespaces(self, h, parts, q):
        return self._rpc("Namespace.List", {})

    def _h_put_namespaces(self, h, parts, q):
        body = h._body()
        return self._rpc("Namespace.Upsert", {
            "name": body.get("Name", "default"),
            "description": body.get("Description", ""),
            "quota": body.get("Quota", "")})

    _h_post_namespaces = _h_put_namespaces

    def _h_get_namespace_id(self, h, parts, q):
        ns = self.agent.server.namespace(parts[1])
        if ns is None:
            raise HTTPError(404, f"namespace not found: {parts[1]}")
        return ns

    def _h_delete_namespace_id(self, h, parts, q):
        return self._rpc("Namespace.Delete", {"name": parts[1]})

    # ------------------------------------------------------------ quotas

    def _h_get_quotas(self, h, parts, q):
        return self._rpc("Quota.List", {})

    def _h_put_quotas(self, h, parts, q):
        from nomad_tpu.structs.namespace import QuotaSpec
        spec = from_wire(QuotaSpec, h._body())
        if not spec.name:
            raise HTTPError(400, "quota spec requires a Name")
        return self._rpc("Quota.Upsert", {"spec": spec})

    _h_post_quotas = _h_put_quotas

    def _h_get_quota_id(self, h, parts, q):
        # /v1/quota/usage/<namespace> | /v1/quota/<name>
        if parts[1] == "usage":
            if len(parts) > 2:
                return {"Namespace": parts[2],
                        "Usage": self._rpc(
                            "Quota.Usage",
                            {"namespace": parts[2]}).get(parts[2], {})}
            return self._rpc("Quota.Usage", {})
        return self._rpc("Quota.GetQuota", {"name": parts[1]})

    _h_put_quota_id = _h_put_quotas
    _h_post_quota_id = _h_put_quotas

    def _h_delete_quota_id(self, h, parts, q):
        resp = self._rpc("Quota.Delete", {"name": parts[1]})
        return resp

    # ------------------------------------------------------------ CSI
    # (reference command/agent/csi_endpoint.go: /v1/volumes,
    #  /v1/volume/csi/<id>, /v1/plugins, /v1/plugin/csi/<id>)

    def _h_get_volumes(self, h, parts, q):
        ns = q.get("namespace", "default")
        return self._rpc("CSIVolume.List", {"namespace": ns})

    def _h_get_volume_id(self, h, parts, q):
        # /v1/volume/csi/<id>
        vol_id = parts[2] if len(parts) > 2 else parts[1]
        vol = self._rpc("CSIVolume.Get", {
            "namespace": q.get("namespace", "default"),
            "volume_id": vol_id})
        out = vol.stub()
        out["ReadAllocs"] = sorted(vol.read_claims)
        out["WriteAllocs"] = sorted(vol.write_claims)
        return out

    def _h_put_volume_id(self, h, parts, q):
        body = h._body()
        from nomad_tpu.structs.csi import CSIVolume
        vols = body.get("Volumes") or [body.get("Volume", body)]
        for v in vols:
            if isinstance(v, dict):
                v = CSIVolume(
                    id=v.get("ID", ""),
                    namespace=v.get("Namespace",
                                    q.get("namespace", "default")),
                    name=v.get("Name", ""),
                    plugin_id=v.get("PluginID", ""),
                    access_mode=v.get("AccessMode", ""),
                    attachment_mode=v.get("AttachmentMode", ""),
                    requested_capabilities=v.get(
                        "RequestedCapabilities", []),
                )
            # re-check against the body's authoritative namespace (the
            # route gate only saw ?namespace=; mirrors _h_put_jobs)
            from nomad_tpu.acl.policy import CAP_CSI_WRITE_VOLUME
            self._require_ns_cap(h, v.namespace, CAP_CSI_WRITE_VOLUME)
            self._rpc("CSIVolume.Register", {"volume": v})
        return {}

    _h_post_volume_id = _h_put_volume_id

    def _h_delete_volume_id(self, h, parts, q):
        vol_id = parts[2] if len(parts) > 2 else parts[1]
        self._rpc("CSIVolume.Deregister", {
            "namespace": q.get("namespace", "default"),
            "volume_id": vol_id,
            "force": q.get("force", "") == "true"})
        return {}

    def _h_get_services(self, h, parts, q):
        """GET /v1/services: grouped nomad-native service listing
        (reference command/agent/service_registration_endpoint.go)."""
        return self._rpc("Service.List",
                         {"namespace": q.get("namespace")})

    def _h_get_service_id(self, h, parts, q):
        """GET /v1/service/<name>: instances of one service."""
        return self._rpc("Service.GetService", {
            "namespace": q.get("namespace", "default"),
            "service_name": parts[1]})

    def _h_delete_service_id(self, h, parts, q):
        """DELETE /v1/service/<name>/<id>."""
        if len(parts) < 3:
            raise HTTPError(400, "service registration id required")
        self._rpc("Service.Delete", {"id": parts[2]})
        return {}

    def _h_get_regions(self, h, parts, q):
        return self._rpc("Status.Regions", {})

    def _h_get_scaling(self, h, parts, q):
        """GET /v1/scaling/policies | /v1/scaling/policy/<id>."""
        if len(parts) >= 2 and parts[1] == "policies":
            return self._rpc("Scaling.ListPolicies",
                             {"namespace": q.get("namespace")})
        if len(parts) >= 3 and parts[1] == "policy":
            return self._rpc("Scaling.GetPolicy", {"id": parts[2]})
        raise HTTPError(404, "no handler for scaling path")

    def _h_get_plugins(self, h, parts, q):
        return self._rpc("CSIPlugin.List", {})

    def _h_get_plugin_id(self, h, parts, q):
        plugin_id = parts[2] if len(parts) > 2 else parts[1]
        plug = self._rpc("CSIPlugin.Get", {"plugin_id": plugin_id})
        return plug.stub()


_STREAMED = object()


def _is_id(s: str) -> bool:
    return bool(re.fullmatch(r"[0-9a-f-]{8,}", s))


def _job_stub(j) -> dict:
    return {"ID": j.id, "Name": j.name, "Namespace": j.namespace,
            "Type": j.type, "Priority": j.priority, "Status": j.status,
            "JobModifyIndex": j.job_modify_index,
            "ModifyIndex": j.modify_index, "Stop": j.stop}


def _node_stub(n) -> dict:
    return {"ID": n.id, "Name": n.name, "Datacenter": n.datacenter,
            "Status": n.status, "SchedulingEligibility":
            n.scheduling_eligibility, "Drain": n.drain_strategy is not None,
            "NodeClass": n.node_class}


def _alloc_stub(a) -> dict:
    return {"ID": a.id, "Name": a.name, "JobID": a.job_id,
            "Namespace": a.namespace,
            "TaskGroup": a.task_group, "NodeID": a.node_id,
            "EvalID": a.eval_id, "ClientStatus": a.client_status,
            "DesiredStatus": a.desired_status,
            "ModifyIndex": a.modify_index}


def _token_stub(t) -> dict:
    return {"AccessorID": t.accessor_id, "Name": t.name, "Type": t.type}


def _token_full(t) -> dict:
    return {"AccessorID": t.accessor_id, "SecretID": t.secret_id,
            "Name": t.name, "Type": t.type, "Policies": list(t.policies),
            "Global": t.global_}
