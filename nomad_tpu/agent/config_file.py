"""Agent HCL configuration files (reference command/agent/config.go +
config_parse.go: HCL files merged with CLI flags).

Supported blocks mirror the reference's layout:

    name       = "agent-1"
    region     = "global"
    datacenter = "dc1"
    data_dir   = "/var/lib/nomad"
    bind_addr  = "0.0.0.0"

    ports { http = 4646 }

    server {
      enabled            = true
      num_schedulers     = 8
      enabled_schedulers = ["service", "batch"]
      heartbeat_grace    = "30s"
    }

    client  { enabled = true }
    acl     { enabled = true }

Values parse with the jobspec HCL tokenizer; CLI flags override file
values (the reference merges files first, flags last)."""
from __future__ import annotations

from typing import List, Optional

from nomad_tpu.agent.agent import AgentConfig
from nomad_tpu.jobspec.hcl import parse_hcl


def _duration_s(v, default: float) -> float:
    if v is None:
        return default
    s = str(v)
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        if s.endswith("h"):
            return float(s[:-1]) * 3600.0
        return float(s)
    except ValueError:
        return default


def load_config_file(path: str,
                     base: Optional[AgentConfig] = None) -> AgentConfig:
    """Parse one HCL agent config file onto `base` (or a fresh default)."""
    with open(path) as f:
        body = parse_hcl(f.read())
    cfg = base or AgentConfig()

    for key, attr in (("name", "name"), ("region", "region"),
                      ("datacenter", "datacenter"),
                      ("data_dir", "data_dir"),
                      ("bind_addr", "http_host")):
        v = body.get(key)
        if v is not None:
            setattr(cfg, attr, str(v))

    ports = body.first("ports")
    if ports is not None and ports.get("http") is not None:
        cfg.http_port = int(ports.get("http"))

    server = body.first("server")
    if server is not None:
        if server.get("enabled") is not None:
            cfg.server_enabled = _truthy(server.get("enabled"))
        if server.get("num_schedulers") is not None:
            cfg.num_schedulers = int(server.get("num_schedulers"))
        es = server.get("enabled_schedulers")
        if isinstance(es, list) and es:
            cfg.enabled_schedulers = [str(x) for x in es]
        if server.get("heartbeat_grace") is not None:
            cfg.heartbeat_ttl = _duration_s(
                server.get("heartbeat_grace"), cfg.heartbeat_ttl)

    client = body.first("client")
    if client is not None and client.get("enabled") is not None:
        cfg.client_enabled = _truthy(client.get("enabled"))

    acl = body.first("acl")
    if acl is not None and acl.get("enabled") is not None:
        cfg.acl_enabled = _truthy(acl.get("enabled"))

    if cfg.server_enabled and cfg.client_enabled:
        cfg.dev_mode = False
    return cfg


def _truthy(v) -> bool:
    return v in (True, "true", "True", 1, "1")
