"""Agent: embeds a Server and/or Client plus the HTTP API
(reference: command/agent/agent.go — setupServer/setupClient; `-dev`
mode runs both in one process with in-memory Raft).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from nomad_tpu.core.server import Server, ServerConfig


@dataclass
class AgentConfig:
    name: str = "agent-1"
    region: str = "global"
    datacenter: str = "dc1"
    server_enabled: bool = True
    client_enabled: bool = False
    dev_mode: bool = True
    http_host: str = "127.0.0.1"
    http_port: int = 4646                 # reference default port
    # address other nodes should use to reach this agent's HTTP API
    # (reference `advertise { http = ... }`); defaults to a best-effort
    # guess — REQUIRED for cross-node alloc fs/logs when binding 0.0.0.0
    http_advertise: Optional[str] = None
    num_schedulers: int = 4
    enabled_schedulers: List[str] = field(
        default_factory=lambda: ["service", "batch", "system", "sysbatch"])
    heartbeat_ttl: float = 10.0
    data_dir: Optional[str] = None
    acl_enabled: bool = False
    node_pool_drivers: List[str] = field(
        default_factory=lambda: ["mock", "raw_exec"])


class Agent:
    """One process: server (control plane) + optional client (node agent)
    + HTTP API.  `-dev` = both, in-memory (command/agent/command.go)."""

    def __init__(self, config: Optional[AgentConfig] = None):
        self.config = config or AgentConfig()
        self.server: Optional[Server] = None
        self.client = None
        self.http: Optional["HTTPServer"] = None
        self._lock = threading.Lock()
        # in-process log ring feeding /v1/agent/monitor (reference
        # command/agent/monitor/monitor.go: a log broker the HTTP monitor
        # endpoint streams from)
        import collections
        import logging

        self.log_ring = collections.deque(maxlen=2048)  # (seq, line)
        self._log_seq = 0
        self._log_cv = threading.Condition()

        agent = self

        class _RingHandler(logging.Handler):
            def emit(self, record):
                try:
                    line = self.format(record)
                except Exception:               # noqa: BLE001
                    return
                with agent._log_cv:
                    agent._log_seq += 1
                    agent.log_ring.append((agent._log_seq, line))
                    agent._log_cv.notify_all()

        handler = _RingHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
        logging.getLogger("nomad_tpu").addHandler(handler)
        logging.getLogger("nomad_tpu").setLevel(logging.INFO)
        self._log_handler = handler

        if self.config.server_enabled:
            self.server = Server(
                ServerConfig(
                    num_schedulers=self.config.num_schedulers,
                    enabled_schedulers=self.config.enabled_schedulers,
                    heartbeat_ttl=self.config.heartbeat_ttl,
                    data_dir=self.config.data_dir,
                    region=self.config.region),
                name=self.config.name)
            if self.config.acl_enabled:
                self.server.enable_acl()
        if self.config.client_enabled:
            try:
                from nomad_tpu.client import Client, ClientConfig
            except ImportError as e:
                raise RuntimeError(
                    "client_enabled requires the nomad_tpu.client "
                    "package") from e
            if self.server is None:
                raise ValueError("remote-server client requires rpc target")
            self.client = Client(
                ClientConfig(node_name=self.config.name + "-client",
                             datacenter=self.config.datacenter,
                             drivers=list(self.config.node_pool_drivers)),
                rpc=self.server.endpoints.handle)

    def start(self) -> None:
        if self.server is not None:
            self.server.start()
        if self.client is not None:
            self.client.start()
        from nomad_tpu.agent.http import HTTPServer
        self.http = HTTPServer(self, host=self.config.http_host,
                               port=self.config.http_port)
        self.http.start()
        if self.client is not None:
            # advertise this agent's HTTP address on the node so servers
            # can forward fs/log reads (Node.HTTPAddr)
            self.client.node.http_addr = self._advertise_addr()
            try:
                self.client.rpc("Node.Register",
                                {"node": self.client.node})
            except Exception:               # noqa: BLE001
                pass

    def _advertise_addr(self) -> str:
        if self.config.http_advertise:
            return self.config.http_advertise
        host = self.http.host
        if host in ("0.0.0.0", "::", ""):
            # wildcard bind is unreachable from other nodes — guess the
            # primary interface address (advertise { http } overrides)
            import socket
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.connect(("10.255.255.255", 1))
                host = s.getsockname()[0]
                s.close()
            except OSError:
                host = "127.0.0.1"
        return f"{host}:{self.http.port}"

    def stop(self) -> None:
        if self.http is not None:
            self.http.stop()
        if self.client is not None:
            self.client.stop()
        if self.server is not None:
            self.server.stop()

    @property
    def http_addr(self) -> str:
        return f"http://{self.http.host}:{self.http.port}"

    def rpc(self, method: str, args: dict,
            consistency: Optional[str] = None):
        """In-process RPC into the embedded server (the agent's RPC
        client; reference command/agent/agent.go RPC passthrough).

        With `consistency` set and a read method, the request is served
        from THIS server's store at a gate-established read point
        (follower reads) instead of forwarding to the leader."""
        if self.server is None:
            raise RuntimeError("agent has no server")
        if consistency is not None:
            from nomad_tpu.serving.gate import READ_METHODS
            if method in READ_METHODS:
                result, _ctx = self.server.read(method, args, consistency)
                return result
        return self.server.rpc_leader(method, args)
