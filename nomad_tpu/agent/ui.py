"""Minimal read-only web UI (the honest stand-in for the reference's
Ember app, ui/app/ ~34k LoC): one dependency-free HTML page served at
/ui that polls the existing /v1 API (jobs, nodes, allocations,
deployments, members) and renders live tables.  Everything it shows
comes through the same HTTP API any client uses — no private hooks."""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  :root { --bg:#0f1419; --panel:#171d24; --fg:#d7dde4; --dim:#8594a5;
          --acc:#22b573; --warn:#e0a030; --bad:#e05252; --line:#252d37; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.5 ui-monospace,SFMono-Regular,Menlo,monospace;
         background:var(--bg); color:var(--fg); }
  header { display:flex; align-items:baseline; gap:16px;
           padding:14px 22px; border-bottom:1px solid var(--line); }
  header h1 { font-size:16px; margin:0; color:var(--acc); }
  header .stat { color:var(--dim); }
  header .stat b { color:var(--fg); }
  main { padding:18px 22px; display:grid; gap:20px; }
  section { background:var(--panel); border:1px solid var(--line);
            border-radius:6px; padding:12px 16px; }
  h2 { font-size:13px; margin:0 0 8px; text-transform:uppercase;
       letter-spacing:.08em; color:var(--dim); }
  table { width:100%; border-collapse:collapse; }
  th, td { text-align:left; padding:4px 10px 4px 0; white-space:nowrap;
           overflow:hidden; text-overflow:ellipsis; max-width:320px; }
  th { color:var(--dim); font-weight:normal; border-bottom:1px solid
       var(--line); }
  .ok   { color:var(--acc); }
  .warn { color:var(--warn); }
  .bad  { color:var(--bad); }
  .dim  { color:var(--dim); }
  #err { color:var(--bad); padding:4px 22px; display:none; }
</style>
</head>
<body>
<header>
  <h1>nomad-tpu</h1>
  <span class="stat">leader <b id="leader">-</b></span>
  <span class="stat">nodes <b id="n-nodes">-</b></span>
  <span class="stat">jobs <b id="n-jobs">-</b></span>
  <span class="stat">allocs <b id="n-allocs">-</b></span>
  <span class="stat dim" id="updated"></span>
</header>
<div id="err"></div>
<main>
  <section><h2>Jobs</h2><table id="jobs"></table></section>
  <section><h2>Allocations</h2><table id="allocs"></table></section>
  <section><h2>Nodes</h2><table id="nodes"></table></section>
  <section><h2>Deployments</h2><table id="deploys"></table></section>
</main>
<script>
const get = p => fetch(p).then(r => { if (!r.ok) throw new Error(p + ": " +
  r.status); return r.json(); });
const cls = s => ({running:"ok", ready:"ok", complete:"dim",
  successful:"ok", pending:"warn", initializing:"warn", failed:"bad",
  down:"bad", lost:"bad", dead:"dim"})[s] || "";
const cell = v => `<td>${v == null ? "" : v}</td>`;
const scell = s => `<td class="${cls(s)}">${s || ""}</td>`;
const short = id => (id || "").slice(0, 8);
function render(tbl, head, rows) {
  document.getElementById(tbl).innerHTML =
    "<tr>" + head.map(h => `<th>${h}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + r.join("") + "</tr>").join("");
}
async function tick() {
  try {
    const [jobs, nodes, allocs, deploys, leader] = await Promise.all([
      get("/v1/jobs"), get("/v1/nodes"), get("/v1/allocations"),
      get("/v1/deployments"), get("/v1/status/leader")]);
    document.getElementById("err").style.display = "none";
    document.getElementById("leader").textContent = leader || "-";
    document.getElementById("n-nodes").textContent = nodes.length;
    document.getElementById("n-jobs").textContent = jobs.length;
    document.getElementById("n-allocs").textContent = allocs.length;
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
    render("jobs", ["ID", "Type", "Priority", "Status"],
      jobs.slice(0, 200).map(j => [cell(j.ID), cell(j.Type),
        cell(j.Priority), scell(j.Status)]));
    render("allocs", ["ID", "Job", "Group", "Node", "Desired", "Client"],
      allocs.slice(0, 200).map(a => [cell(short(a.ID)), cell(a.JobID),
        cell(a.TaskGroup), cell(short(a.NodeID)),
        scell(a.DesiredStatus), scell(a.ClientStatus)]));
    render("nodes", ["ID", "Name", "DC", "Class", "Status", "Eligibility"],
      nodes.slice(0, 200).map(n => [cell(short(n.ID)), cell(n.Name),
        cell(n.Datacenter), cell(n.NodeClass || "-"), scell(n.Status),
        scell(n.SchedulingEligibility)]));
    render("deploys", ["ID", "Job", "Status", "Description"],
      deploys.slice(0, 200).map(d => [cell(short(d.ID)), cell(d.JobID),
        scell(d.Status), cell(d.StatusDescription)]));
  } catch (e) {
    const el = document.getElementById("err");
    el.textContent = String(e);
    el.style.display = "block";
  }
}
tick();
setInterval(tick, 2000);
</script>
</body>
</html>
"""
