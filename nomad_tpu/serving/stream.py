"""NDJSON event-stream pump (reference nomad/stream/ndjson.go: a writer
goroutine draining a subscription with periodic `{}` heartbeats).

`EventStreamer.run` drains one broker subscription into a caller
`write(bytes)` sink.  Heartbeats are emitted only when the configured
interval elapses with no events (``?heartbeat=`` go-duration per
request, ``NOMAD_TPU_STREAM_HEARTBEAT`` seconds as the default) — the
old behavior of one `{}` per idle poll quadrupled idle-stream bytes.

The `stream.subscriber_stall` chaos point injects consumer stalls here:
with it firing, the broker's bounded queues must evict + catch up, never
grow without limit.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

from nomad_tpu import chaos, knobs
from nomad_tpu.api.codec import to_wire
from nomad_tpu.core.events import Subscription


def default_heartbeat() -> float:
    return knobs.get_float("NOMAD_TPU_STREAM_HEARTBEAT")


class EventStreamer:
    """Pumps one subscription to one sink for up to `duration` seconds."""

    def __init__(self, sub: Subscription,
                 heartbeat: Optional[float] = None,
                 filter_fn: Optional[Callable] = None):
        self.sub = sub
        self.heartbeat = heartbeat if heartbeat and heartbeat > 0 \
            else default_heartbeat()
        self.filter_fn = filter_fn          # e.g. ACL namespace visibility
        self.sent = 0
        self.heartbeats = 0

    def run(self, write: Callable[[bytes], None], duration: float) -> None:
        deadline = time.monotonic() + duration
        last_sent = time.monotonic()
        poll = min(0.25, self.heartbeat)
        while time.monotonic() < deadline:
            ev = self.sub.next(timeout=poll)
            if ev is not None and self.filter_fn is not None \
                    and not self.filter_fn(ev):
                ev = None                   # filtered, but not a heartbeat
            chaos.maybe_delay("stream.subscriber_stall")
            if ev is None:
                now = time.monotonic()
                if now - last_sent >= self.heartbeat:
                    write(b"{}\n")          # reference heartbeat frame
                    self.heartbeats += 1
                    last_sent = now
                continue
            d = ev.to_dict()
            d["Payload"] = to_wire(d["Payload"])
            write((json.dumps({"Index": ev.index, "Events": [d]})
                   + "\n").encode())
            self.sent += 1
            last_sent = time.monotonic()
