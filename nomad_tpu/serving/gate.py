"""Consistency-mode resolution for the serving plane.

The gate turns a per-request consistency mode into a *read point*: the
index the local store must reflect before the read is served, plus the
staleness metadata the HTTP layer reports back
(``X-Nomad-LastContact`` / ``X-Nomad-KnownLeader``).

Mode semantics (reference api/api.go QueryOptions + nomad/rpc.go
blockingRPC):

- ``consistent`` — linearizable via the full ReadIndex protocol: the
  leader runs a heartbeat quorum round (batched across concurrent
  readers) and returns its commit index; a follower forwards one small
  RPC, then waits ``last_applied >= index`` locally before serving.
- default — linearizable via the leader lease: while the leader's last
  quorum ack is younger than ``election_timeout * (1 - skew)`` the read
  point costs zero network rounds on the leader and one forwarded RPC
  (no quorum round) on a follower.
- ``stale`` — serve immediately from the local store, whatever its
  index; the caller learns how stale via LastContact/KnownLeader.

Failure shape: on a minority partition, ``stale`` keeps serving while
``consistent``/default fail fast — an unreachable leader raises
immediately; a vacant leadership (election in flight) is retried only
until the caller's timeout.
"""
from __future__ import annotations

import time
from typing import Optional

from nomad_tpu.raft import NotLeaderError
from nomad_tpu.raft.transport import Unreachable
from nomad_tpu.rpc.endpoints import RpcError

CONSISTENT = "consistent"
DEFAULT = "default"
STALE = "stale"
_MODES = (CONSISTENT, DEFAULT, STALE)

# Read-only RPC methods a follower may serve from its local store once a
# read point is established.  Everything else (writes, leader-local
# state like Secrets, scheduler dry-runs) still forwards to the leader.
READ_METHODS = frozenset({
    "Status.Ping", "Status.Leader", "Status.Members", "Status.Peers",
    "Status.Regions",
    "Job.GetJob", "Job.List", "Job.Summary", "Job.Allocations",
    "Job.Evaluations", "Job.ScaleStatus",
    "Node.List", "Node.GetNode", "Node.GetAllocs", "Node.GetClientAllocs",
    "Eval.GetEval", "Eval.List",
    "Alloc.GetAlloc", "Alloc.List",
    "Deployment.List", "Deployment.GetDeployment",
    "CSIVolume.List", "CSIVolume.Get", "CSIPlugin.List", "CSIPlugin.Get",
    "Operator.SchedulerGetConfiguration",
    "Namespace.List", "Quota.List", "Quota.GetQuota", "Quota.Usage",
    "Search.PrefixSearch",
    "Scaling.ListPolicies", "Scaling.GetPolicy",
    "Service.List", "Service.GetService",
})


def mode_from_query(q: dict) -> str:
    """Per-request mode from HTTP query params (last value wins):
    ``?consistent`` beats ``?stale=true``; absent both is the default."""
    if "consistent" in q and q.get("consistent", "") not in ("0", "false"):
        return CONSISTENT
    if "stale" in q and q.get("stale", "") not in ("0", "false"):
        return STALE
    return DEFAULT


class ReadContext:
    """An established read point: the serve-at index plus the staleness
    metadata emitted on the response."""

    __slots__ = ("index", "known_leader", "last_contact_ms", "mode")

    def __init__(self, index: int, known_leader: bool,
                 last_contact_ms: float, mode: str):
        self.index = index
        self.known_leader = known_leader
        self.last_contact_ms = last_contact_ms
        self.mode = mode


class ReadGate:
    def __init__(self, server):
        self.server = server

    def begin_read(self, mode: str = DEFAULT,
                   timeout: float = 5.0) -> ReadContext:
        """Establish a read point for `mode`; returns once the LOCAL
        store may serve the read.  Raises on an unreachable/vacant
        leadership for the linearizable modes (stale never raises)."""
        if mode not in _MODES:
            raise ValueError(f"unknown consistency mode {mode!r}")
        s = self.server
        raft = s.raft
        if raft is None:                      # dev mode: trivially current
            return ReadContext(s.store.latest_index, True, 0.0, mode)
        integ = getattr(raft, "integrity", None)
        if integ is not None and integ.quarantined:
            # divergence quarantine: this replica's store failed the
            # digest vote — NO local read (stale, lease or consistent)
            # may be served until digest-verified re-admission.  It
            # still replicates and votes; callers retry a healthy peer.
            raise RpcError(
                "quarantined",
                f"replica integrity quarantine "
                f"({integ.quarantine_reason}): local reads refused "
                f"until digest-verified re-admission",
                leader=raft.leader_id, retry_after=1.0)
        if mode == STALE:
            return ReadContext(s.store.latest_index,
                               raft.leader_id is not None,
                               raft.last_contact_ms(), STALE)
        lease_ok = mode == DEFAULT
        deadline = time.monotonic() + timeout
        while True:
            try:
                return self._read_point(lease_ok, deadline, mode)
            except Unreachable:
                raise                         # partitioned: fail fast
            except (NotLeaderError, RpcError) as e:
                if isinstance(e, RpcError) \
                        and e.kind not in ("no_leader", "not_leader"):
                    raise
                # leadership transfer in flight: retry inside the
                # caller's wait cap, never past it
                if time.monotonic() + 0.05 >= deadline:
                    raise
                time.sleep(0.025)

    def _read_point(self, lease_ok: bool, deadline: float,
                    mode: str) -> ReadContext:
        s, raft = self.server, self.server.raft
        remaining = max(0.05, deadline - time.monotonic())
        if raft.is_leader:
            idx = raft.read_index(timeout=remaining, lease_ok=lease_ok)
            # the leader must ALSO wait for its own apply loop: a follower
            # can apply a committed entry before the leader does, and a
            # linearizable read served from the leader's lagging store
            # would miss an entry a gated follower read already exposed
            if not raft.wait_applied(idx, timeout=max(
                    0.05, deadline - time.monotonic())):
                raise TimeoutError(
                    f"read index {idx} not applied within the wait cap "
                    f"(applied={raft.last_applied})")
            return ReadContext(idx, True, 0.0, mode)
        resp = s.rpc_leader("Raft.ReadIndex",
                            {"lease": lease_ok, "timeout": remaining})
        idx = int(resp["index"])
        if not raft.wait_applied(idx, timeout=max(
                0.05, deadline - time.monotonic())):
            raise TimeoutError(
                f"read index {idx} not applied within the wait cap "
                f"(applied={raft.last_applied})")
        return ReadContext(idx, True, raft.last_contact_ms(), mode)
