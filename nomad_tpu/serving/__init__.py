"""Read-scalable serving plane (reference: nomad/rpc.go forward +
blockingOptions, api/api.go QueryOptions{AllowStale}, and the
stream/ndjson.go event pipeline).

Every server — leader or follower — can answer read RPCs from its local
state store once a *read point* is established.  The gate
(`serving.gate.ReadGate`) resolves the per-request consistency mode:

- ``consistent``: full Raft ReadIndex (heartbeat quorum confirmation).
- default: leader-lease read — zero network rounds in steady state.
- ``stale``: serve immediately from any server, reporting staleness via
  ``X-Nomad-LastContact`` / ``X-Nomad-KnownLeader``.

`serving.stream.EventStreamer` is the NDJSON pump for /v1/event/stream
over the backpressured broker in `core/events.py`.
"""
from nomad_tpu.serving.gate import (
    CONSISTENT, DEFAULT, STALE, READ_METHODS,
    ReadContext, ReadGate, mode_from_query,
)
from nomad_tpu.serving.stream import EventStreamer

__all__ = [
    "CONSISTENT", "DEFAULT", "STALE", "READ_METHODS",
    "ReadContext", "ReadGate", "mode_from_query", "EventStreamer",
]
