"""State layer (reference: nomad/state/ — StateStore over go-memdb).

A versioned in-memory store with snapshot-at-index semantics, secondary
indexes, watch hooks for the control loops, and an embedded ClusterMatrix
columnar mirror kept incrementally up to date (SURVEY.md section 2.7 item 7:
'state store hot reads -> host-side columnar mirror producing the dense
node x taskgroup matrices shipped to device').
"""

from nomad_tpu.state.store import StateStore, StateSnapshot

__all__ = ["StateStore", "StateSnapshot"]
