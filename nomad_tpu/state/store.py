"""StateStore: versioned in-memory MVCC-style store.

Reference: nomad/state/state_store.go (StateStore:83, Snapshot:190,
SnapshotMinIndex:217, UpsertPlanResults:337) and the table schemata in
nomad/state/schema.go:116-1107.  Differences by design:

- go-memdb's immutable radix trees give O(1) snapshots; here objects are
  treated as immutable-once-inserted (writers always insert copies) and a
  snapshot shallow-copies the table dicts, memoized per index so concurrent
  scheduler workers share one snapshot until the next write.
- The dense ClusterMatrix mirror is maintained inline on every node/alloc
  write — the TPU analog of memdb watchsets feeding blocking queries.
"""
from __future__ import annotations

import threading
import time as _time
from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from nomad_tpu.analysis import race
from nomad_tpu.encode.matrixizer import ClusterMatrix
from nomad_tpu.structs import (
    Allocation,
    AllocClientStatus,
    AllocDesiredStatus,
    Deployment,
    DeploymentStatus,
    Evaluation,
    EvalStatus,
    Job,
    JobStatus,
    Node,
    SchedulerConfiguration,
)
from nomad_tpu.structs.evaluation import EvalTrigger
from nomad_tpu.structs.namespace import (
    Namespace, QuotaSpec, alloc_quota_usage, usage_add)
from nomad_tpu.structs.node import NodeStatus, compute_node_class
from nomad_tpu.structs.plan import Plan, PlanResult
from nomad_tpu.utils import requires_lock


class JobSummary:
    """Per-job per-taskgroup alloc status counts (reference
    structs.JobSummary, maintained by state_store alloc writes)."""

    def __init__(self, job_id: str, namespace: str = "default"):
        self.job_id = job_id
        self.namespace = namespace
        self.summary: Dict[str, Dict[str, int]] = {}
        self.children = {"pending": 0, "running": 0, "dead": 0}
        self.create_index = 0
        self.modify_index = 0

    def group(self, tg: str) -> Dict[str, int]:
        return self.summary.setdefault(tg, {
            "queued": 0, "complete": 0, "failed": 0,
            "running": 0, "starting": 0, "lost": 0, "unknown": 0})

    def to_dict(self) -> dict:
        return {"job_id": self.job_id, "namespace": self.namespace,
                "summary": {k: dict(v) for k, v in self.summary.items()},
                "children": dict(self.children),
                "create_index": self.create_index,
                "modify_index": self.modify_index}


class StateSnapshot:
    """A consistent read-only view at one index."""

    @requires_lock("_lock")
    def __init__(self, store: "StateStore"):
        # caller (StateStore.snapshot) holds store._lock while we copy
        self.index = store.latest_index
        self.nodes: Dict[str, Node] = dict(store._nodes)
        self.jobs: Dict[Tuple[str, str], Job] = dict(store._jobs)
        self.evals: Dict[str, Evaluation] = dict(store._evals)
        self.allocs: Dict[str, Allocation] = dict(store._allocs)
        self.deployments: Dict[str, Deployment] = dict(store._deployments)
        self._allocs_by_job = {k: set(v) for k, v in store._allocs_by_job.items()}
        self._allocs_by_node = {k: set(v) for k, v in store._allocs_by_node.items()}
        self.scheduler_config = store.scheduler_config
        # the matrix is shared (incremental); schedulers use it read-only
        # together with per-eval used_override deltas
        self.matrix = store.matrix
        self._store = store

    # --- read API mirroring the reference's State interface
    # (scheduler/scheduler.go:67-116)

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self.nodes.get(node_id)

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self.jobs.get((namespace, job_id))

    def ready_nodes_in_dcs(self, datacenters: List[str]) -> List[Node]:
        dcs = set(datacenters)
        return [n for n in self.nodes.values()
                if n.ready() and n.datacenter in dcs]

    def allocs_by_job(self, namespace: str, job_id: str,
                      all_allocs: bool = True) -> List[Allocation]:
        ids = self._allocs_by_job.get((namespace, job_id), ())
        return [self.allocs[i] for i in ids]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._allocs_by_node.get(node_id, ())
        return [self.allocs[i] for i in ids]

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self.deployments.get(deployment_id)

    def latest_deployment_by_job_id(self, namespace: str, job_id: str) -> Optional[Deployment]:
        best = None
        for d in self.deployments.values():
            if d.namespace == namespace and d.job_id == job_id:
                if best is None or d.create_index > best.create_index:
                    best = d
        return best

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self.evals.get(eval_id)

    # CSI reads go through the live store: claims move through the
    # serialized applier/FSM, so the checker wants the freshest view
    # (the reference checker also re-reads state inside the worker's
    # snapshot, feasible.go:276-300)
    def csi_volume_by_id(self, namespace: str, vol_id: str):
        return self._store.csi_volume_by_id(namespace, vol_id)

    def csi_plugin_by_id(self, plugin_id: str):
        return self._store.csi_plugin_by_id(plugin_id)


class StateStore:
    # Lock discipline, enforced statically by nomad_tpu.analysis
    # (lock-discipline checker): every read/write of the attrs below must
    # happen inside `with <store>._lock:` or a @requires_lock method.
    _LOCK_NAME = "_lock"
    _LOCK_ALIASES = ("_index_cv",)       # Condition wrapping the same RLock
    # happens-before (nomad_tpu.analysis): the plan-id dedup ring is
    # mutated by every FSM apply (leader loop, restore replay, tests'
    # direct commits); the runtime race detector traces it.
    _RACE_TRACED = {"_applied_plan_ids_set": "_lock"}
    _LOCK_PROTECTED = frozenset({
        "_nodes", "_jobs", "_job_versions", "_evals", "_allocs",
        "_deployments", "_job_summaries", "_allocs_by_job",
        "_allocs_by_node", "_allocs_by_eval", "_evals_by_job",
        "_namespaces", "_acl_policies", "_acl_tokens", "_acl_by_secret",
        "_csi_volumes", "_csi_plugins", "_scaling_events", "_services",
        "_services_by_alloc", "_applied_plan_ids", "_applied_plan_ids_set",
        "_snapshot_cache", "_live_names", "_quota_specs", "_quota_usage",
    })
    # snapshot-completeness (nomad_tpu.analysis): the replication
    # contract for every _LOCK_PROTECTED table.  A table named in
    # neither map must appear in BOTH the snapshot record and the
    # restore path; a derived index is instead rebuilt through the
    # named builder — the SAME row constructor the apply path uses, so
    # restore cannot drift from apply — and an ephemeral cache
    # legitimately dies with the process.
    _SNAPSHOT_DERIVED = {
        "_allocs_by_job": "_index_alloc_locked",
        "_allocs_by_node": "_index_alloc_locked",
        "_allocs_by_eval": "_index_alloc_locked",
        "_live_names": "_index_alloc_locked",
        "_evals_by_job": "_index_eval_locked",
        "_acl_by_secret": "_index_acl_token_locked",
        "_services_by_alloc": "_index_service_locked",
        "_applied_plan_ids_set": "_reindex_applied_plan_ids_locked",
    }
    _SNAPSHOT_EPHEMERAL = frozenset({"_snapshot_cache"})
    # canonical-form (nomad_tpu.analysis): replicated tables whose
    # byte-identity depends on a single mutation path (fixed key order,
    # delete-at-zero); every in-place write outside the named
    # canonicalizer is a finding.
    _CANONICAL = {"_quota_usage": "_quota_usage_add"}

    def __init__(self):
        self._lock = threading.RLock()
        self._index_cv = threading.Condition(self._lock)
        self.latest_index = 0
        self._nodes: Dict[str, Node] = {}
        self._jobs: Dict[Tuple[str, str], Job] = {}
        self._job_versions: Dict[Tuple[str, str], List[Job]] = defaultdict(list)
        self._evals: Dict[str, Evaluation] = {}
        self._allocs: Dict[str, Allocation] = {}
        self._deployments: Dict[str, Deployment] = {}
        self._job_summaries: Dict[Tuple[str, str], JobSummary] = {}
        self._allocs_by_job: Dict[Tuple[str, str], Set[str]] = defaultdict(set)
        self._allocs_by_node: Dict[str, Set[str]] = defaultdict(set)
        self._allocs_by_eval: Dict[str, Set[str]] = defaultdict(set)
        # derived, never serialized: (namespace, job_id, name) -> ids of
        # non-terminal allocs holding that name (the plan-apply
        # duplicate-name guard reads it per placement, so it must be
        # O(1), not a scan of the job's alloc set)
        self._live_names: Dict[Tuple[str, str, str], Set[str]] = {}
        self._evals_by_job: Dict[Tuple[str, str], Set[str]] = defaultdict(set)
        self.scheduler_config = SchedulerConfiguration()
        # namespaces table (reference nomad/state/schema.go namespaces)
        self._namespaces: Dict[str, Namespace] = {
            "default": Namespace(name="default",
                                 description="Default shared namespace")}
        # quota specs + replicated usage accounting.  Usage is maintained
        # inside the same apply cone as `_live_names` (alloc liveness
        # transitions) so every replica derives byte-identical tables;
        # all-zero namespace entries are deleted for a canonical form.
        self._quota_specs: Dict[str, QuotaSpec] = {}
        self._quota_usage: Dict[str, Dict[str, int]] = {}
        # ACL tables (reference schema.go acl_policy / acl_token)
        self._acl_policies: Dict[str, object] = {}
        self._acl_tokens: Dict[str, object] = {}       # by accessor_id
        self._acl_by_secret: Dict[str, object] = {}
        # CSI tables (reference schema.go csi_volumes / csi_plugins)
        self._csi_volumes: Dict[Tuple[str, str], object] = {}   # (ns, id)
        self._csi_plugins: Dict[str, object] = {}
        # scaling event ring per (ns, job, group) (reference schema.go
        # scaling_event; capped like structs.JobTrackedScalingEvents)
        self._scaling_events: Dict[Tuple[str, str, str], List[object]] = {}
        # nomad-native service registrations, keyed by registration id
        # (reference schema.go service_registrations)
        self._services: Dict[str, object] = {}
        self._services_by_alloc: Dict[str, Set[str]] = defaultdict(set)
        self.matrix = ClusterMatrix()
        # readers outside the store (the placement engine's basis copies)
        # take this lock to avoid tearing a half-applied commit
        self.matrix.lock = self._lock
        self._snapshot_cache: Optional[StateSnapshot] = None
        # watchers: fn(table: str, obj) called after commit, outside hot loops
        self._watchers: List[Callable[[str, object], None]] = []
        # plan-id dedup ring: APPLY_PLAN_RESULTS entries replayed after a
        # leader failover (raft log re-application onto a restored
        # snapshot) must commit at most once.  Bounded FIFO; old ids age
        # out long after any replay window.
        self._applied_plan_ids: List[str] = []
        self._applied_plan_ids_set: Set[str] = set()
        self._applied_plan_ids_cap = 8192

    # ------------------------------------------------------------ plumbing

    def watch(self, fn: Callable[[str, object], None]) -> None:
        self._watchers.append(fn)

    def _notify(self, table: str, obj) -> None:
        for fn in self._watchers:
            fn(table, obj)

    @requires_lock("_lock")
    def _bump(self, index: int) -> None:
        if index <= self.latest_index:
            index = self.latest_index  # idempotent replay keeps max
        self.latest_index = max(self.latest_index, index)
        self._snapshot_cache = None
        self._index_cv.notify_all()

    def snapshot(self) -> StateSnapshot:
        """Memoized per index (reference Snapshot, state_store.go:190)."""
        with self._lock:
            if self._snapshot_cache is None:
                self._snapshot_cache = StateSnapshot(self)
            return self._snapshot_cache

    def snapshot_min_index(self, index: int, timeout: float = 5.0) -> Optional[StateSnapshot]:
        """Block until state has caught up to `index` (reference
        SnapshotMinIndex, state_store.go:217 — gates scheduling on Raft
        catch-up)."""
        with self._index_cv:
            if not self._index_cv.wait_for(
                    lambda: self.latest_index >= index, timeout=timeout):
                return None
            return self.snapshot()

    def wait_for_index(self, index: int, timeout: float = 5.0) -> bool:
        with self._index_cv:
            return self._index_cv.wait_for(
                lambda: self.latest_index >= index, timeout=timeout)

    # ------------------------------------------------------------ nodes

    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            node.modify_index = index
            if node.id not in self._nodes:
                node.create_index = index
            if not node.computed_class:
                node.computed_class = compute_node_class(node)
            self._nodes[node.id] = node
            self.matrix.upsert_node(node)
            self._update_csi_plugins_for_node(index, node)
            self._bump(index)
        self._notify("nodes", node)

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(node_id, None)
            self.matrix.remove_node(node_id)
            for plug in list(self._csi_plugins.values()):
                plug.nodes.pop(node_id, None)
                plug.controllers.pop(node_id, None)
                if not plug.nodes and not plug.controllers:
                    del self._csi_plugins[plug.id]
            self._bump(index)
        if node:
            self._notify("nodes", node)

    @requires_lock("_lock")
    def _update_csi_plugins_for_node(self, index: int, node: Node) -> None:
        """Derive csi_plugins rows from node fingerprints (reference
        state_store.go updateNodeCSIPlugins)."""
        from nomad_tpu.structs.csi import CSIPlugin
        seen = set()
        for pid, info in node.csi_node_plugins.items():
            plug = self._csi_plugins.get(pid)
            if plug is None:
                plug = self._csi_plugins[pid] = CSIPlugin(
                    id=pid, provider=info.get("provider", ""),
                    create_index=index)
            plug.nodes[node.id] = {
                "healthy": bool(info.get("healthy", False)),
                "max_volumes": int(info.get("max_volumes", 0) or 0),
            }
            plug.modify_index = index
            seen.add(pid)
        for pid, info in node.csi_controller_plugins.items():
            plug = self._csi_plugins.get(pid)
            if plug is None:
                plug = self._csi_plugins[pid] = CSIPlugin(
                    id=pid, provider=info.get("provider", ""),
                    create_index=index)
            plug.controllers[node.id] = {
                "healthy": bool(info.get("healthy", False))}
            plug.controller_required = True
            plug.modify_index = index
            seen.add(pid)
        # plugin rows this node no longer fingerprints
        for pid, plug in list(self._csi_plugins.items()):
            if pid in seen:
                continue
            plug.nodes.pop(node.id, None)
            plug.controllers.pop(node.id, None)
            if not plug.nodes and not plug.controllers:
                del self._csi_plugins[pid]

    def update_node_status(self, index: int, node_id: str, status: str,
                           updated_at: float = 0.0) -> None:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                return
            node = _shallow_copy_node(old)
            node.status = status
            node.status_updated_at = updated_at
            node.modify_index = index
            self._nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump(index)
        self._notify("nodes", node)

    def update_node_statuses_many(self, index: int, updates) -> None:
        """Batched status/liveness transitions — one lock pass for a
        whole heartbeat-coalescer flush (the node-plane analogue of
        upsert_plan_results_many), so a 10K-agent fleet's steady-state
        heartbeat writes cost O(batches), not O(nodes), store passes.
        Each update dict carries node_id/status/updated_at with the
        same per-node semantics as update_node_status."""
        changed = []
        with self._lock:
            for u in updates:
                old = self._nodes.get(u["node_id"])
                if old is None:
                    continue
                node = _shallow_copy_node(old)
                node.status = u["status"]
                node.status_updated_at = u.get("updated_at", 0.0)
                node.modify_index = index
                self._nodes[u["node_id"]] = node
                self.matrix.upsert_node(node)
                changed.append(node)
            if changed:
                self._bump(index)
        for node in changed:
            self._notify("nodes", node)

    def update_node_fingerprints_many(self, index: int, updates) -> None:
        """Batched device/attribute re-fingerprints — one lock pass for
        a whole coalescer flush (mirrors update_node_statuses_many), so
        a fleet-wide fingerprint storm costs O(batches) store passes
        and O(flush-ticks) raft entries, not O(changes) Node.Register
        round-trips.  Each update dict carries node_id plus optional
        devices / attributes deltas."""
        import copy as _copy
        changed = []
        with self._lock:
            for u in updates:
                old = self._nodes.get(u["node_id"])
                if old is None:
                    continue
                node = _shallow_copy_node(old)
                if "devices" in u:
                    # node_resources is shared by the shallow copy —
                    # copy it too or the old record aliases the new
                    # device list and MVCC readers see torn state.
                    node.node_resources = _copy.copy(old.node_resources)
                    node.node_resources.devices = u["devices"]
                if "attributes" in u:
                    attrs = dict(old.attributes)
                    attrs.update(u["attributes"])
                    node.attributes = attrs
                node.computed_class = compute_node_class(node)
                node.modify_index = index
                self._nodes[u["node_id"]] = node
                self.matrix.upsert_node(node)
                changed.append(node)
            if changed:
                self._bump(index)
        for node in changed:
            self._notify("nodes", node)

    def update_node_drain(self, index: int, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> None:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                return
            node = _shallow_copy_node(old)
            node.drain_strategy = drain_strategy
            if drain_strategy is not None:
                node.scheduling_eligibility = "ineligible"
            elif mark_eligible:
                node.scheduling_eligibility = "eligible"
            node.modify_index = index
            self._nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump(index)
        self._notify("nodes", node)

    def update_node_eligibility(self, index: int, node_id: str, eligibility: str) -> None:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                return
            node = _shallow_copy_node(old)
            node.scheduling_eligibility = eligibility
            node.modify_index = index
            self._nodes[node_id] = node
            self.matrix.upsert_node(node)
            self._bump(index)
        self._notify("nodes", node)

    def chaos_bitflip(self, u: float = 0.0):
        """Silently corrupt ONE replicated record (the `store.bitflip`
        / `disk.silent_corrupt` chaos payload): a copy-on-write of the
        victim with a `\\x00` appended to an inert string field — no
        index bump, no notify, no dirty mark.  Exactly the class of
        divergence the integrity plane exists to catch; invisible to
        everything except a digest walk.  Tables are visited in a fixed
        order (namespaces first — `default` always exists) so drills
        are deterministic; `u` (a seeded chaos uniform) picks the
        victim record within the table.  Returns "table/key" or None
        if every candidate table is empty."""
        import copy as _copy
        with self._lock:
            for name, table in (("namespaces", self._namespaces),
                                ("nodes", self._nodes),
                                ("jobs", self._jobs)):
                if not table:
                    continue
                keys = sorted(table)
                key = keys[int(u * len(keys)) % len(keys)]
                rec = _copy.copy(table[key])
                if name == "namespaces":
                    rec.description = (rec.description or "") + "\x00"
                else:
                    rec.name = (rec.name or "") + "\x00"
                table[key] = rec
                return "%s/%s" % (name, key)
        return None

    def nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def node_by_id(self, node_id: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    # ------------------------------------------------------------ jobs

    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            job.canonicalize()
            # submit_time is stamped at PROPOSE time (Server.register_job)
            # and carried in the raft log payload: stamping it here would
            # run inside fsm.apply, where a wall-clock read makes every
            # replica/replay produce a different value.
            key = (job.namespace, job.id)
            existing = self._jobs.get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.version = existing.version + 1
            else:
                job.create_index = index
                job.version = 0
            job.modify_index = index
            job.job_modify_index = index
            if job.status not in (JobStatus.DEAD,):
                job.status = JobStatus.PENDING if not job.stop else JobStatus.DEAD
            self._jobs[key] = job
            self._job_versions[key].append(job)
            if len(self._job_versions[key]) > 6:   # JobTrackedVersions
                self._job_versions[key].pop(0)
            if key not in self._job_summaries:
                js = JobSummary(job.id, job.namespace)
                js.create_index = index
                self._job_summaries[key] = js
            for tg in job.task_groups:
                self._job_summaries[key].group(tg.name)
            self._bump(index)
        self._notify("jobs", job)

    def delete_job(self, index: int, namespace: str, job_id: str) -> None:
        with self._lock:
            job = self._jobs.pop((namespace, job_id), None)
            self._job_versions.pop((namespace, job_id), None)
            self._job_summaries.pop((namespace, job_id), None)
            self._bump(index)
        if job:
            self._notify("jobs_deregistered", job)

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get((namespace, job_id))

    def mark_job_stability(self, index: int, namespace: str, job_id: str,
                           version: int, stable: bool) -> None:
        """Job.Stability RPC / deployment success path: flip `stable` on a
        specific version WITHOUT bumping the job version (reference
        UpdateJobStability)."""
        with self._lock:
            key = (namespace, job_id)
            versions = self._job_versions.get(key, [])
            for i, j in enumerate(versions):
                if j.version == version:
                    u = j.copy()
                    u.stable = stable
                    u.version = j.version
                    u.create_index = j.create_index
                    u.modify_index = index
                    versions[i] = u
                    if self._jobs.get(key) is j or (
                            self._jobs.get(key) is not None
                            and self._jobs[key].version == version):
                        self._jobs[key] = u
                    break
            self._bump(index)

    def job_versions(self, namespace: str, job_id: str) -> List[Job]:
        """All tracked versions, newest first (reference JobVersionsByID)."""
        with self._lock:
            return sorted(self._job_versions.get((namespace, job_id), ()),
                          key=lambda j: j.version, reverse=True)

    def job_version(self, namespace: str, job_id: str, version: int) -> Optional[Job]:
        with self._lock:
            for j in self._job_versions.get((namespace, job_id), ()):
                if j.version == version:
                    return j
        return None

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def job_summary(self, namespace: str, job_id: str) -> Optional[JobSummary]:
        with self._lock:
            return self._job_summaries.get((namespace, job_id))

    # ------------------------------------------------------------ evals

    def upsert_evals(self, index: int, evals: Iterable[Evaluation]) -> None:
        # create_time/modify_time are stamped at propose time and ride in
        # the log payload — reading the clock here diverges replicas.
        out = []
        with self._lock:
            for e in evals:
                if e.id not in self._evals:
                    e.create_index = index
                if not e.modify_time:
                    e.modify_time = e.create_time
                e.modify_index = index
                self._evals[e.id] = e
                self._index_eval_locked(e)
                out.append(e)
            self._bump(index)
        for e in out:
            self._notify("evals", e)

    def delete_eval(self, index: int, eval_ids: Iterable[str],
                    alloc_ids: Iterable[str] = ()) -> None:
        with self._lock:
            for eid in eval_ids:
                e = self._evals.pop(eid, None)
                if e is not None:
                    self._evals_by_job[(e.namespace, e.job_id)].discard(eid)
            for aid in alloc_ids:
                self._drop_alloc(aid)
            self._bump(index)

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        with self._lock:
            return self._evals.get(eval_id)

    def evals(self) -> List[Evaluation]:
        with self._lock:
            return list(self._evals.values())

    def allocs(self) -> List[Allocation]:
        with self._lock:
            return list(self._allocs.values())

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        with self._lock:
            return [self._evals[i]
                    for i in self._evals_by_job.get((namespace, job_id), ())]

    # ---------------------------------------------------- scaling events

    MAX_SCALING_EVENTS = 100   # reference structs.JobTrackedScalingEvents

    def upsert_scaling_event(self, index: int, namespace: str, job_id: str,
                             group: str, event) -> None:
        with self._lock:
            ring = self._scaling_events.setdefault(
                (namespace, job_id, group), [])
            ring.insert(0, event)
            del ring[self.MAX_SCALING_EVENTS:]
            self._bump(index)

    def scaling_events_by_job(self, namespace: str, job_id: str):
        """{group: [ScalingEvent, newest first]}"""
        with self._lock:
            return {g: list(ev) for (ns, jid, g), ev in
                    self._scaling_events.items()
                    if ns == namespace and jid == job_id}

    def scaling_policies(self, namespace: Optional[str] = None):
        """[(job, group, ScalingPolicy)] over live jobs (the reference
        stores policies in their own table; here they live on the job,
        the single source of truth)."""
        with self._lock:
            out = []
            for j in self._jobs.values():
                if namespace is not None and j.namespace != namespace:
                    continue
                if j.stopped():
                    continue
                for tg in j.task_groups:
                    if tg.scaling is not None:
                        out.append((j, tg.name, tg.scaling))
            return out

    # ----------------------------------------------- service registrations

    def upsert_service_registrations(self, index: int, services) -> None:
        """services: [ServiceRegistration] (reference
        state_store_service_registration.go UpsertServiceRegistrations)."""
        with self._lock:
            for sr in services:
                self._services[sr.id] = sr
                self._index_service_locked(sr)
            self._bump(index)
        for sr in services:
            self._notify("services", sr)

    def delete_service_registrations(self, index: int, ids=None,
                                     alloc_id: Optional[str] = None) -> None:
        with self._lock:
            doomed = set(ids or ())
            if alloc_id is not None:
                doomed |= self._services_by_alloc.get(alloc_id, set())
            removed = []
            # sorted: set order varies with hash randomization, and pop
            # order shapes dict layout -> snapshot bytes must not care
            for sid in sorted(doomed):
                sr = self._services.pop(sid, None)
                if sr is not None:
                    self._services_by_alloc[sr.alloc_id].discard(sid)
                    removed.append(sr)
            self._bump(index)
        for sr in removed:
            self._notify("services", sr)

    def services(self, namespace: Optional[str] = None):
        with self._lock:
            return [s for s in self._services.values()
                    if namespace is None or s.namespace == namespace]

    def services_by_name(self, namespace: str, name: str):
        with self._lock:
            return [s for s in self._services.values()
                    if s.namespace == namespace and s.service_name == name]

    def services_by_alloc(self, alloc_id: str):
        with self._lock:
            return [self._services[i]
                    for i in self._services_by_alloc.get(alloc_id, ())]

    # ------------------------------------------- derived index builders
    #
    # The ONLY row constructors for _SNAPSHOT_DERIVED tables: the apply
    # path calls them incrementally, snapshot restore calls them per
    # restored row.  Keeping both paths on one function is what lets a
    # restored follower replay the rest of the log byte-identically to
    # a survivor that applied it live (snapshot-completeness checker).

    @requires_lock("_lock")
    def _index_eval_locked(self, e: Evaluation) -> None:
        self._evals_by_job[(e.namespace, e.job_id)].add(e.id)

    @requires_lock("_lock")
    def _index_service_locked(self, sr) -> None:
        self._services_by_alloc[sr.alloc_id].add(sr.id)

    @requires_lock("_lock")
    def _index_acl_token_locked(self, token) -> None:
        self._acl_by_secret[token.secret_id] = token

    @requires_lock("_lock")
    def _index_alloc_locked(self, a: Allocation) -> None:
        self._allocs_by_job[(a.namespace, a.job_id)].add(a.id)
        self._allocs_by_node[a.node_id].add(a.id)
        self._allocs_by_eval[a.eval_id].add(a.id)
        if a.terminal_status():
            self._live_name_unset(a)
        else:
            self._live_names.setdefault(
                (a.namespace, a.job_id, a.name), set()).add(a.id)

    @requires_lock("_lock")
    def _reindex_applied_plan_ids_locked(self) -> None:
        race.write("StateStore._applied_plan_ids_set", self)
        self._applied_plan_ids_set = set(self._applied_plan_ids)

    # ------------------------------------------------------------ allocs

    @requires_lock("_lock")
    def _drop_alloc(self, alloc_id: str) -> None:
        a = self._allocs.pop(alloc_id, None)
        if a is None:
            return
        self._allocs_by_job[(a.namespace, a.job_id)].discard(alloc_id)
        self._allocs_by_node[a.node_id].discard(alloc_id)
        self._allocs_by_eval[a.eval_id].discard(alloc_id)
        self._live_name_unset(a)
        if not a.terminal_status():
            self._quota_usage_add(a.namespace, alloc_quota_usage(a), -1)
        self.matrix.remove_alloc(alloc_id)

    @requires_lock("_lock")
    def _insert_alloc(self, index: int, a: Allocation) -> None:
        prev = self._allocs.get(a.id)
        if prev is not None:
            a.create_index = prev.create_index
            # client-set fields survive server-side rewrites (reference
            # UpsertAllocs keeps ClientStatus unless explicitly set)
        else:
            a.create_index = index
        if a.job is None:
            a.job = self._jobs.get((a.namespace, a.job_id))
        a.modify_index = index
        self._allocs[a.id] = a
        self._index_alloc_locked(a)
        # quota usage rides the same liveness transition as _live_names:
        # decrement with the PREVIOUS copy's resources (an in-place
        # update may have changed them), increment with the new one
        prior_live = prev is not None and not prev.terminal_status()
        new_live = not a.terminal_status()
        if prior_live:
            self._quota_usage_add(prev.namespace, alloc_quota_usage(prev), -1)
        if new_live:
            self._quota_usage_add(a.namespace, alloc_quota_usage(a), +1)
        self.matrix.upsert_alloc(a)
        self._update_summary(a, prev)

    @requires_lock("_lock")
    def _live_name_unset(self, a: Allocation) -> None:
        key = (a.namespace, a.job_id, a.name)
        ids = self._live_names.get(key)
        if ids is not None:
            ids.discard(a.id)
            if not ids:
                del self._live_names[key]

    @requires_lock("_lock")
    def _update_summary(self, a: Allocation, prev: Optional[Allocation]) -> None:
        key = (a.namespace, a.job_id)
        js = self._job_summaries.get(key)
        if js is None:
            js = JobSummary(a.job_id, a.namespace)
            self._job_summaries[key] = js
        g = js.group(a.task_group)

        def bucket(al: Optional[Allocation]) -> Optional[str]:
            if al is None:
                return None
            return {
                AllocClientStatus.PENDING: "starting",
                AllocClientStatus.RUNNING: "running",
                AllocClientStatus.COMPLETE: "complete",
                AllocClientStatus.FAILED: "failed",
                AllocClientStatus.LOST: "lost",
                AllocClientStatus.UNKNOWN: "unknown",
            }.get(al.client_status)

        pb, nb = bucket(prev), bucket(a)
        if pb == nb:
            return
        if pb and g.get(pb, 0) > 0:
            g[pb] -= 1
        if nb:
            g[nb] = g.get(nb, 0) + 1

    def upsert_allocs(self, index: int, allocs: Iterable[Allocation]) -> None:
        out = []
        with self._lock:
            for a in allocs:
                self._insert_alloc(index, a)
                out.append(a)
            self._bump(index)
        for a in out:
            self._notify("allocs", a)

    def update_allocs_from_client(self, index: int, updates: Iterable[Allocation]) -> None:
        """Client status updates merge onto the server copy (reference
        UpdateAllocsFromClient / nomadFSM ApplyAllocClientUpdate)."""
        out = []
        with self._lock:
            for u in updates:
                existing = self._allocs.get(u.id)
                if existing is None:
                    continue
                a = existing.copy()
                a.client_status = u.client_status
                a.client_description = u.client_description
                a.task_states = dict(u.task_states)
                if u.deployment_status is not None:
                    a.deployment_status = u.deployment_status
                a.modify_index = index
                self._insert_alloc(index, a)
                out.append(a)
            self._bump(index)
        for a in out:
            self._notify("allocs", a)

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        with self._lock:
            return self._allocs.get(alloc_id)

    def allocs_by_job(self, namespace: str, job_id: str) -> List[Allocation]:
        with self._lock:
            return [self._allocs[i]
                    for i in self._allocs_by_job.get((namespace, job_id), ())]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        with self._lock:
            return [self._allocs[i] for i in self._allocs_by_node.get(node_id, ())]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        with self._lock:
            return [self._allocs[i] for i in self._allocs_by_eval.get(eval_id, ())]

    # ------------------------------------------------------------ deployments

    def upsert_deployment(self, index: int, d: Deployment) -> None:
        # timestamps stamped at propose time (core/deployments.py) and
        # carried in the log payload; no clock reads under fsm.apply
        with self._lock:
            if d.id not in self._deployments:
                d.create_index = index
            if not d.modify_time:
                d.modify_time = d.create_time
            d.modify_index = index
            self._deployments[d.id] = d
            self._bump(index)
        self._notify("deployments", d)

    def delete_deployment(self, index: int, deployment_id: str) -> None:
        with self._lock:
            self._deployments.pop(deployment_id, None)
            self._bump(index)

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        with self._lock:
            return self._deployments.get(deployment_id)

    def deployments(self) -> List[Deployment]:
        with self._lock:
            return list(self._deployments.values())

    def latest_deployment_by_job_id(self, namespace: str,
                                    job_id: str) -> Optional[Deployment]:
        with self._lock:
            best = None
            for d in self._deployments.values():
                if d.namespace == namespace and d.job_id == job_id:
                    if best is None or d.create_index > best.create_index:
                        best = d
            return best

    # ------------------------------------------------------------ config

    def set_scheduler_config(self, index: int, cfg: SchedulerConfiguration) -> None:
        with self._lock:
            cfg.modify_index = index
            self.scheduler_config = cfg
            self._bump(index)

    # ------------------------------------------------------------ namespaces

    def upsert_namespace(self, index: int, name: str, description: str = "",
                         quota: str = "") -> None:
        with self._lock:
            existing = self._namespaces.get(name)
            ns = Namespace(name=name, description=description, quota=quota)
            ns.create_index = existing.create_index if existing else index
            ns.modify_index = index
            self._namespaces[name] = ns
            self._bump(index)

    def delete_namespace(self, index: int, name: str) -> None:
        with self._lock:
            if name == "default":
                raise ValueError("default namespace cannot be deleted")
            for ns, _ in self._jobs:
                if ns == name:
                    raise ValueError(f"namespace {name!r} has jobs")
            self._namespaces.pop(name, None)
            self._bump(index)

    def namespaces(self) -> List[Namespace]:
        with self._lock:
            return list(self._namespaces.values())

    def namespace(self, name: str) -> Optional[Namespace]:
        with self._lock:
            return self._namespaces.get(name)

    # ------------------------------------------------------------ quotas

    @requires_lock("_lock")
    def _quota_usage_add(self, namespace: str, vec: Dict[str, int],
                         sign: int) -> None:
        """Canonical-form usage accounting: an entry is either absent or
        a full {cpu, memory_mb, devices, allocs} dict, created with a
        fixed key order, deleted when it returns to all-zero — so the
        table is byte-identical across replicas that applied the same
        log, independent of the path taken."""
        u = self._quota_usage.get(namespace)
        if u is None:
            u = self._quota_usage[namespace] = {
                "cpu": 0, "memory_mb": 0, "devices": 0, "allocs": 0}
        usage_add(u, vec, sign)
        if not any(u.values()):
            del self._quota_usage[namespace]

    @requires_lock("_lock")
    def _quota_admits_locked(self, a: Allocation) -> Tuple[bool, str]:
        """Would placing `a` keep its namespace inside its quota?
        Returns (admitted, quota_spec_name)."""
        ns = self._namespaces.get(a.namespace)
        if ns is None or not ns.quota:
            return True, ""
        spec = self._quota_specs.get(ns.quota)
        if spec is None:
            return True, ""
        would = dict(self._quota_usage.get(a.namespace) or {})
        usage_add(would, alloc_quota_usage(a), +1)
        return spec.admits(would), ns.quota

    def upsert_quota_spec(self, index: int, spec: QuotaSpec) -> None:
        with self._lock:
            existing = self._quota_specs.get(spec.name)
            spec.create_index = existing.create_index if existing else index
            spec.modify_index = index
            self._quota_specs[spec.name] = spec
            self._bump(index)

    def delete_quota_spec(self, index: int, name: str) -> None:
        with self._lock:
            for ns in self._namespaces.values():
                if ns.quota == name:
                    raise ValueError(
                        f"quota {name!r} referenced by namespace {ns.name!r}")
            self._quota_specs.pop(name, None)
            self._bump(index)

    def quota_spec(self, name: str) -> Optional[QuotaSpec]:
        with self._lock:
            return self._quota_specs.get(name)

    def quota_specs(self) -> List[QuotaSpec]:
        with self._lock:
            return list(self._quota_specs.values())

    def quota_usage(self, namespace: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._quota_usage.get(namespace) or {})

    def quota_usages(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {ns: dict(u) for ns, u in self._quota_usage.items()}

    # ------------------------------------------------------------ ACL

    def upsert_acl_policy(self, index: int, policy) -> None:
        with self._lock:
            self._acl_policies[policy.name] = policy
            self._bump(index)

    def delete_acl_policy(self, index: int, name: str) -> None:
        with self._lock:
            self._acl_policies.pop(name, None)
            self._bump(index)

    def acl_policy(self, name: str):
        with self._lock:
            return self._acl_policies.get(name)

    def acl_policies(self) -> list:
        with self._lock:
            return list(self._acl_policies.values())

    def upsert_acl_token(self, index: int, token) -> None:
        with self._lock:
            token.modify_index = index
            if not token.create_index:
                token.create_index = index
            self._acl_tokens[token.accessor_id] = token
            self._index_acl_token_locked(token)
            self._bump(index)

    def delete_acl_token(self, index: int, accessor_id: str) -> None:
        with self._lock:
            t = self._acl_tokens.pop(accessor_id, None)
            if t is not None:
                self._acl_by_secret.pop(t.secret_id, None)
            self._bump(index)

    def acl_token(self, accessor_id: str):
        with self._lock:
            return self._acl_tokens.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        with self._lock:
            return self._acl_by_secret.get(secret_id)

    def acl_tokens(self) -> list:
        with self._lock:
            return list(self._acl_tokens.values())

    # ------------------------------------------------------------ plan results

    # ------------------------------------------------------------- CSI

    def upsert_csi_volume(self, index: int, vol) -> None:
        with self._lock:
            key = (vol.namespace, vol.id)
            existing = self._csi_volumes.get(key)
            if existing is None:
                vol.create_index = index
            elif existing.in_use():
                # re-registering an in-use volume must not drop its live
                # claims (the reference register path preserves claims;
                # losing them would admit a second writer immediately)
                vol.read_claims = existing.read_claims
                vol.write_claims = existing.write_claims
                vol.past_claims = existing.past_claims
                vol.access_mode = existing.access_mode or vol.access_mode
                vol.create_index = existing.create_index
            vol.modify_index = index
            self._csi_volumes[key] = vol
            self._refresh_volume_health(vol)
            self._bump(index)
        self._notify("csi_volumes", vol)

    def deregister_csi_volume(self, index: int, namespace: str,
                              vol_id: str, force: bool = False) -> None:
        with self._lock:
            vol = self._csi_volumes.get((namespace, vol_id))
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if vol.in_use() and not force:
                raise ValueError(f"volume {vol_id} in use")
            del self._csi_volumes[(namespace, vol_id)]
            self._bump(index)
        self._notify("csi_volumes", vol)

    def csi_volume_by_id(self, namespace: str, vol_id: str):
        with self._lock:
            vol = self._csi_volumes.get((namespace, vol_id))
            if vol is not None:
                self._refresh_volume_health(vol)
            return vol

    def csi_volumes(self, namespace: Optional[str] = None) -> List:
        with self._lock:
            vols = [v for (ns, _), v in sorted(self._csi_volumes.items())
                    if namespace in (None, ns)]
            for v in vols:
                self._refresh_volume_health(v)
            return vols

    def csi_volumes_by_plugin(self, plugin_id: str) -> List:
        with self._lock:
            return [v for v in self._csi_volumes.values()
                    if v.plugin_id == plugin_id]

    def csi_plugin_by_id(self, plugin_id: str):
        with self._lock:
            return self._csi_plugins.get(plugin_id)

    def csi_plugins(self) -> List:
        with self._lock:
            return [self._csi_plugins[k]
                    for k in sorted(self._csi_plugins)]

    def csi_volume_claim(self, index: int, namespace: str, vol_id: str,
                         claim) -> None:
        """Take or release a claim (reference CSIVolumeClaim FSM apply).
        A claim whose state is past 'taken' is a release step; fully
        released claims leave the claim maps."""
        from nomad_tpu.structs import csi as csistructs
        with self._lock:
            vol = self._csi_volumes.get((namespace, vol_id))
            if vol is None:
                raise KeyError(f"volume {vol_id} not found")
            if claim.state == csistructs.CLAIM_STATE_TAKEN:
                vol.claim(claim)
            else:
                vol.release(claim.alloc_id)
            vol.modify_index = index
            self._bump(index)
        self._notify("csi_volumes", vol)

    def csi_volume_counts_by_node(self) -> Dict[str, Dict[str, int]]:
        """node_id -> {plugin id -> live-claim volume count}, one pass
        over the volumes table (dense-checker bulk variant of
        node_csi_volume_count)."""
        counts: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for vol in self._csi_volumes.values():
                nodes = {c.node_id
                         for c in list(vol.read_claims.values()) +
                         list(vol.write_claims.values())}
                for nid in nodes:
                    per = counts.setdefault(nid, {})
                    per[vol.plugin_id] = per.get(vol.plugin_id, 0) + 1
        return counts

    @requires_lock("_lock")
    def _refresh_volume_health(self, vol) -> None:
        """Denormalize plugin health onto the volume (reference
        CSIVolumeDenormalizePlugins): schedulable tracks node-plugin
        health, plus controller health when controllers are required."""
        plug = self._csi_plugins.get(vol.plugin_id)
        if plug is None:
            vol.schedulable = False
            vol.nodes_healthy = 0
            vol.controllers_healthy = 0
            return
        vol.nodes_healthy = plug.nodes_healthy
        vol.nodes_expected = len(plug.nodes)
        vol.controllers_healthy = plug.controllers_healthy
        vol.controllers_expected = len(plug.controllers)
        vol.controller_required = plug.controller_required
        ok = vol.nodes_healthy > 0
        if plug.controller_required:
            ok = ok and vol.controllers_healthy > 0
        vol.schedulable = ok

    @requires_lock("_lock")
    def _take_csi_claims_for_alloc(self, index: int, alloc) -> None:
        """Claims for a placed allocation's CSI volume requests (the
        reference claims from the client csi_hook via the
        CSIVolume.Claim RPC; here the commit path takes them so the
        scheduler's view is updated atomically with the plan)."""
        from nomad_tpu.structs import csi as csistructs
        job = alloc.job
        if job is None:
            return
        tg = next((t for t in job.task_groups
                   if t.name == alloc.task_group), None)
        if tg is None:
            return
        for req in tg.volumes.values():
            if req.type != "csi":
                continue
            vol = self._csi_volumes.get((job.namespace, req.source))
            if vol is None:
                continue
            mode = csistructs.CLAIM_READ if req.read_only \
                else csistructs.CLAIM_WRITE
            vol.claim(csistructs.CSIVolumeClaim(
                alloc_id=alloc.id, node_id=alloc.node_id, mode=mode,
                state=csistructs.CLAIM_STATE_TAKEN))
            vol.modify_index = index

    @requires_lock("_lock")
    def _upsert_plan_result_locked(self, index: int,
                                   result: "AppliedPlanResults",
                                   touched: list) -> None:
        """One plan's writes; caller holds self._lock and notifies for
        `touched` after releasing it."""
        plan_id = getattr(result, "plan_id", "")  # pre-dedup pickles lack it
        if plan_id:
            race.write("StateStore._applied_plan_ids_set", self)
            if plan_id in self._applied_plan_ids_set:
                return
            self._applied_plan_ids.append(plan_id)
            self._applied_plan_ids_set.add(plan_id)
            if len(self._applied_plan_ids) > self._applied_plan_ids_cap:
                evicted = self._applied_plan_ids.pop(0)
                self._applied_plan_ids_set.discard(evicted)
        for a in result.alloc_updates:      # stops/evicts
            existing = self._allocs.get(a.id)
            if existing is not None and a.job is None:
                a.job = existing.job
            self._insert_alloc(index, a)
            touched.append(a)
        for a in result.allocs_to_place:    # placements
            # live-name guard: racing plans for one redelivered eval can
            # both pass the submit-time token gate (the lease expires
            # after the first enqueue but before its commit), and the
            # loser would duplicate a name the winner already placed.
            # Every legitimate same-name placement stops its predecessor
            # in the same plan (alloc_updates apply above) or replaces a
            # terminal alloc, so a live holder here is always a racer.
            # Updates of existing allocs (same id) always apply.  System
            # and sysbatch allocs all share one name by design (one per
            # node), so their duplicates are scoped to the node.
            if a.id not in self._allocs:
                holders = self._live_names.get(
                    (a.namespace, a.job_id, a.name))
                if holders:
                    per_node = a.job is not None and \
                        a.job.type in ("system", "sysbatch")
                    if not per_node:
                        continue
                    if any(o is not None and o.node_id == a.node_id
                           for o in (self._allocs.get(i)
                                     for i in holders)):
                        continue
                # quota guard: the authoritative, replica-deterministic
                # admission check.  The applier already checked at propose
                # time against its overlay, but two leaders across a churn
                # window can each propose within-budget plans that only
                # overflow combined — the log serializes them and the
                # SECOND one is dropped here, identically on every
                # replica.  Stops in this same plan applied above
                # (alloc_updates), so same-plan frees are counted.
                admitted, quota_name = self._quota_admits_locked(a)
                if not admitted:
                    # pre-quota pickles lack the attr; drop silently then
                    getattr(result, "quota_dropped", []).append(
                        (a.id, quota_name))
                    continue
            self._insert_alloc(index, a)
            self._take_csi_claims_for_alloc(index, a)
            touched.append(a)
        for a in result.allocs_preempted:
            existing = self._allocs.get(a.id)
            if existing is not None and a.job is None:
                a.job = existing.job
            self._insert_alloc(index, a)
            touched.append(a)
        if result.deployment is not None:
            d = result.deployment
            # one deployment per job version: concurrent/redelivered evals
            # for the same registration can both carry a fresh deployment
            # (each planned against a snapshot that predates the other's
            # commit).  The first to apply wins; the loser's placements
            # join it, instead of stranding a duplicate RUNNING deployment
            # no allocs will ever report health for.
            winner = None
            if d.id not in self._deployments:
                for other in self._deployments.values():
                    if (other.id != d.id
                            and other.namespace == d.namespace
                            and other.job_id == d.job_id
                            and other.job_version == d.job_version
                            and other.job_create_index == d.job_create_index
                            and other.status not in (DeploymentStatus.FAILED,
                                                     DeploymentStatus.CANCELLED)):
                        winner = other
                        break
            if winner is not None:
                for a in (result.allocs_to_place + result.alloc_updates):
                    if a.deployment_id == d.id:
                        a.deployment_id = winner.id
            else:
                if d.id not in self._deployments:
                    d.create_index = index
                d.modify_index = index
                self._deployments[d.id] = d
        for upd in result.deployment_updates:
            d = self._deployments.get(upd["deployment_id"])
            if d is not None:
                d = d.copy()
                d.status = upd["status"]
                d.status_description = upd.get("description", "")
                d.modify_index = index
                self._deployments[d.id] = d

    def upsert_plan_results(self, index: int, result: "AppliedPlanResults") -> None:
        """Apply a committed plan (reference UpsertPlanResults,
        state_store.go:337): denormalize stopped/preempted allocs, insert
        placements, attach deployment updates."""
        touched: list = []
        with self._lock:
            self._upsert_plan_result_locked(index, result, touched)
            self._bump(index)
        for a in touched:
            self._notify("allocs", a)

    def upsert_plan_results_many(self, index: int,
                                 results) -> None:
        """Apply a coalesced batch of committed plans under ONE lock
        acquisition and ONE index bump — the applier's batch commit.
        Plans in a batch touch disjoint alloc ids (each scheduler eval
        owns its placements), so sharing an index is safe: upserts are
        keyed by alloc id and create_index is preserved on update."""
        touched: list = []
        with self._lock:
            for result in results:
                self._upsert_plan_result_locked(index, result, touched)
            self._bump(index)
        for a in touched:
            self._notify("allocs", a)


class AppliedPlanResults:
    """The payload of the ApplyPlanResults Raft message."""

    def __init__(self, alloc_updates=None, allocs_to_place=None,
                 allocs_preempted=None, deployment=None, deployment_updates=None,
                 eval_id: str = "", plan_id: str = ""):
        self.alloc_updates = alloc_updates or []
        self.allocs_to_place = allocs_to_place or []
        self.allocs_preempted = allocs_preempted or []
        self.deployment = deployment
        self.deployment_updates = deployment_updates or []
        self.eval_id = eval_id
        self.plan_id = plan_id
        # filled by the FSM when the authoritative quota check drops a
        # placement: [(alloc_id, quota_spec_name)]
        self.quota_dropped: list = []


def _shallow_copy_node(node: Node) -> Node:
    import copy as _copy
    return _copy.copy(node)
