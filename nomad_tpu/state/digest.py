"""Canonical state encoding shared by the scenario battery and the
replica-integrity plane.

The chaos cells prove FSM byte-identity by comparing canonicalized
snapshots (`canon`); the runtime integrity plane proves the SAME
property online by exchanging per-table digests (`table_digest`) on
heartbeat acks.  Both definitions of "identical" live here, on one
encoding, so they can never drift: two snapshots are canon-equal if and
only if every per-table digest matches (modulo hash collisions — the
property test in tests/test_integrity.py asserts both directions
empirically).

Encoding rules (the battery has relied on these since PR 3):

- tables are visited in sorted key order — never set/dict-arrival order
- list tables compare as a SORTED multiset of standalone pickles: the
  big snapshot pickle's string memoization means two byte-different
  blobs can hold equal values, so each item is re-pickled on its own
- dict tables compare per sorted key, values re-pickled standalone
- scalars compare as their standalone pickle

Digests are length-framed SHA-256 over the canonical encoding, so "item
boundary" ambiguity can't alias two different tables onto one digest.
"""
from __future__ import annotations

import hashlib
import pickle
import struct
from typing import Dict


def canon_table(val):
    """Canonical form of ONE snapshot table value: a sorted list of
    standalone pickles (list tables), a sorted-key dict of standalone
    pickles (dict tables), or the standalone pickle (scalars)."""
    if isinstance(val, list):
        return sorted(pickle.dumps(v) for v in val)
    if isinstance(val, dict):
        return {k: pickle.dumps(v) for k, v in sorted(val.items())}
    return pickle.dumps(val)


def canon(blob: bytes) -> dict:
    """Canonical form of a whole FSM snapshot blob; equality here IS the
    battery's byte-identity gate."""
    data = pickle.loads(blob)
    out = {}
    for key, val in sorted(data.items()):
        out[key] = canon_table(val)
    return out


def _frame(h, b: bytes) -> None:
    h.update(struct.pack("<I", len(b)))
    h.update(b)


def table_digest(val) -> str:
    """Digest of one table value over its canonical form (16 hex chars:
    64 bits — plenty for a 3..5-replica equality vote, small enough to
    ride every heartbeat ack)."""
    h = hashlib.sha256()
    c = canon_table(val)
    if isinstance(c, list):
        h.update(b"L")
        for b in c:
            _frame(h, b)
    elif isinstance(c, dict):
        h.update(b"D")
        for k, b in c.items():        # insertion order == sorted keys
            _frame(h, pickle.dumps(k))
            _frame(h, b)
    else:
        h.update(b"S")
        _frame(h, c)
    return h.hexdigest()[:16]


def tables_digests(tables: dict) -> Dict[str, str]:
    """Per-table digests of a snapshot record dict (the pre-pickle form
    `NomadFSM.snapshot_tables` returns, or `pickle.loads(blob)`)."""
    out: Dict[str, str] = {}
    for key in sorted(tables):
        out[key] = table_digest(tables[key])
    return out


def blob_digests(blob: bytes) -> Dict[str, str]:
    """Per-table digests straight from a snapshot blob (leader side of
    anti-entropy repair: the expected digest of the streamed state)."""
    return tables_digests(pickle.loads(blob))


def combine(per_table: Dict[str, str]) -> str:
    """One rolling digest over the per-table digests, visited in sorted
    table order (16 hex chars)."""
    h = hashlib.sha256()
    for key in sorted(per_table):
        _frame(h, key.encode())
        _frame(h, per_table[key].encode())
    return h.hexdigest()[:16]


def first_divergence(a: Dict[str, str], b: Dict[str, str]):
    """First table (sorted order) whose digests differ, or None.  Used
    to name the divergent table in the integrity alarm."""
    for key in sorted(set(a) | set(b)):
        if a.get(key) != b.get(key):
            return key
    return None
