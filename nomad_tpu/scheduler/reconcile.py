"""Allocation reconciler: desired-state diff engine for service/batch jobs.

Reference: scheduler/reconcile.go (allocReconciler:39, Compute:204,
computeGroup:383) and reconcile_util.go (allocSet filters).  Host-side pure
set logic — not a hot loop (SURVEY.md section 7 item 4); the output drives
the dense placement kernel.

Given a job (possibly stopped / a new version), its existing allocations,
node taint info, and the active deployment, computes per task group:
place / stop / ignore / migrate / in-place-update / destructive-update /
canary / disconnect / reconnect sets, plus deployment status updates and
delayed-reschedule follow-up evals.
"""
from __future__ import annotations

import math
import time as _time
import uuid

from nomad_tpu.utils import generate_uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.structs import (
    Allocation,
    AllocClientStatus,
    AllocDesiredStatus,
    Deployment,
    DeploymentState,
    DeploymentStatus,
    Evaluation,
    EvalStatus,
    Job,
    TaskGroup,
)
from nomad_tpu.structs.alloc import alloc_name
from nomad_tpu.structs.evaluation import EvalTrigger
from nomad_tpu.structs.job import JobType, ReschedulePolicy

# desired-description strings (reference structs allocs' DesiredDescription)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
ALLOC_LOST = "alloc was lost since its node is down"
ALLOC_UNKNOWN = "alloc is unknown since its node is disconnected"
ALLOC_CANARY = "alloc is a canary"
ALLOC_RECONNECTED = "alloc is reconnecting"
ALLOC_DUPLICATE = "alloc duplicates another allocation's name"


@dataclass
class PlacementRequest:
    task_group: str
    name: str                     # "<job>.<group>[i]"
    previous_alloc: Optional[Allocation] = None
    is_canary: bool = False
    is_destructive: bool = False
    is_rescheduling: bool = False
    min_job_version: int = 0


@dataclass
class StopRequest:
    alloc: Allocation
    status_description: str = ""
    client_status: str = ""
    followup_eval_id: str = ""


@dataclass
class ReconcileResults:
    """Reference reconcileResults (reconcile.go:97-137)."""
    place: List[PlacementRequest] = field(default_factory=list)
    stop: List[StopRequest] = field(default_factory=list)
    inplace_update: List[Allocation] = field(default_factory=list)
    destructive_stop: List[StopRequest] = field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = field(default_factory=dict)
    disconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    reconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: List[dict] = field(default_factory=list)
    desired_followup_evals: Dict[str, List[Evaluation]] = field(default_factory=dict)
    desired_tg_updates: Dict[str, dict] = field(default_factory=dict)

    def tg_update(self, tg: str) -> dict:
        return self.desired_tg_updates.setdefault(tg, {
            "ignore": 0, "place": 0, "migrate": 0, "stop": 0,
            "in_place_update": 0, "destructive_update": 0, "canary": 0,
            "preemptions": 0})


def tasks_updated(a: TaskGroup, b: TaskGroup) -> bool:
    """Whether moving from group a to b needs a destructive update
    (reference scheduler/util.go:488 tasksUpdated)."""
    if len(a.tasks) != len(b.tasks):
        return True
    if _nets_updated(a.networks, b.networks):
        return True
    if (a.ephemeral_disk.size_mb != b.ephemeral_disk.size_mb
            or a.ephemeral_disk.sticky != b.ephemeral_disk.sticky):
        return True
    bt = {t.name: t for t in b.tasks}
    for t in a.tasks:
        o = bt.get(t.name)
        if o is None:
            return True
        if (t.driver != o.driver or t.config != o.config or t.env != o.env
                or t.artifacts != o.artifacts or t.meta != o.meta
                or t.templates != o.templates or t.vault != o.vault):
            return True
        ra, rb = t.resources, o.resources
        if (ra.cpu != rb.cpu or ra.cores != rb.cores
                or ra.memory_mb != rb.memory_mb
                or ra.memory_max_mb != rb.memory_max_mb
                or len(ra.devices) != len(rb.devices)
                or _nets_updated(ra.networks, rb.networks)):
            return True
    return False


def _nets_updated(a, b) -> bool:
    if len(a) != len(b):
        return True
    for na, nb in zip(a, b):
        if na.mode != nb.mode or na.mbits != nb.mbits:
            return True
        if ([(p.label, p.value, p.to) for p in na.reserved_ports]
                != [(p.label, p.value, p.to) for p in nb.reserved_ports]):
            return True
        if ([(p.label, p.to) for p in na.dynamic_ports]
                != [(p.label, p.to) for p in nb.dynamic_ports]):
            return True
    return False


def reschedule_delay(policy: ReschedulePolicy, attempt: int) -> float:
    """Backoff for the next reschedule attempt (reference
    structs.ReschedulePolicy delay functions)."""
    if policy.delay_function == "constant":
        d = policy.delay_s
    elif policy.delay_function == "exponential":
        d = policy.delay_s * (2 ** attempt)
    elif policy.delay_function == "fibonacci":
        a, b = policy.delay_s, policy.delay_s
        for _ in range(attempt):
            a, b = b, a + b
        d = a
    else:
        d = policy.delay_s
    if policy.max_delay_s:
        d = min(d, policy.max_delay_s)
    return d


def should_reschedule_now(alloc: Allocation, policy: Optional[ReschedulePolicy],
                          now: float, is_batch: bool) -> Tuple[bool, float]:
    """-> (eligible, wait_until).  wait_until 0 means immediately.
    Mirrors Allocation.ShouldReschedule / NextRescheduleTime."""
    if policy is None:
        return False, 0.0
    if alloc.desired_transition.should_force_reschedule():
        return True, 0.0
    if alloc.client_status != AllocClientStatus.FAILED:
        return False, 0.0
    events = alloc.reschedule_tracker.events if alloc.reschedule_tracker else []
    attempt = len(events)
    if not policy.unlimited:
        if policy.attempts == 0:
            return False, 0.0
        window_start = now - policy.interval_s
        recent = [e for e in events if e.reschedule_time >= window_start]
        if len(recent) >= policy.attempts:
            return False, 0.0
    delay = reschedule_delay(policy, attempt) if not is_batch else 0.0
    if is_batch or delay <= 0:
        return True, 0.0
    fail_time = _alloc_fail_time(alloc, now)
    ready_at = fail_time + delay
    return True, (ready_at if ready_at > now else 0.0)


def _alloc_fail_time(alloc: Allocation, now: float) -> float:
    latest = 0.0
    for ts in alloc.task_states.values():
        latest = max(latest, ts.finished_at)
    return latest or now


class AllocReconciler:
    def __init__(self, job: Optional[Job], job_id: str, existing: List[Allocation],
                 tainted_nodes: Dict[str, object], deployment: Optional[Deployment],
                 eval_id: str = "", batch: bool = False, now: Optional[float] = None,
                 eval_priority: int = 50, supports_disconnected: bool = True):
        self.job = job
        self.job_id = job_id
        self.existing = existing
        self.tainted = tainted_nodes        # node_id -> Node (down/draining/disconnected)
        self.deployment = deployment
        self.eval_id = eval_id
        self.batch = batch
        self.now = now if now is not None else _time.time()
        self.eval_priority = eval_priority
        self.results = ReconcileResults()
        self.deployment_paused = bool(
            deployment and deployment.status in (DeploymentStatus.PAUSED,
                                                 DeploymentStatus.PENDING))
        self.deployment_failed = bool(
            deployment and deployment.status == DeploymentStatus.FAILED)

    # ------------------------------------------------------------- compute

    def compute(self) -> ReconcileResults:
        job_stopped = self.job is None or self.job.stopped()

        # cancel an ACTIVE deployment for a stopped job or older version;
        # terminal deployments (failed/successful/cancelled) are left alone
        # and must not gate the next rollout via stale paused/failed flags
        if self.deployment is not None and not self.deployment.active():
            self.deployment = None
            self.deployment_paused = False
            self.deployment_failed = False
        if self.deployment is not None:
            cancel = False
            desc = ""
            if job_stopped:
                cancel, desc = True, "Cancelled because job is stopped"
            elif self.job.version != self.deployment.job_version:
                cancel, desc = True, DeploymentStatus.DESC_NEWER_JOB
            if cancel:
                self.results.deployment_updates.append({
                    "deployment_id": self.deployment.id,
                    "status": DeploymentStatus.CANCELLED,
                    "description": desc})
                self.deployment = None

        if job_stopped:
            self._stop_all()
            return self.results

        groups = {tg.name: tg for tg in self.job.task_groups}
        by_group: Dict[str, List[Allocation]] = {g: [] for g in groups}
        for a in self.existing:
            if a.task_group in by_group:
                by_group[a.task_group].append(a)
            else:
                # group removed from the job
                if not a.terminal_status():
                    self.results.stop.append(StopRequest(a, ALLOC_NOT_NEEDED))

        deployment_complete = True
        for name, tg in groups.items():
            complete = self._compute_group(tg, by_group[name])
            deployment_complete = deployment_complete and complete

        # an alloc chosen for stop must not also ride along as an update
        stopped_ids = {sr.alloc.id for sr in self.results.stop}
        stopped_ids |= {sr.alloc.id for sr in self.results.destructive_stop}
        self.results.inplace_update = [
            u for u in self.results.inplace_update if u.id not in stopped_ids]

        self._finalize_deployment(deployment_complete)
        return self.results

    def _stop_all(self) -> None:
        for a in self.existing:
            if not a.terminal_status():
                desc = ("alloc not needed due to job being stopped"
                        if self.job is not None else "alloc not needed as job was purged")
                self.results.stop.append(StopRequest(a, desc))
                if self.job is not None:
                    self.results.tg_update(a.task_group)["stop"] += 1

    # ------------------------------------------------------- group compute

    def _filter_by_tainted(self, allocs: List[Allocation], tg: TaskGroup):
        """Split allocs by node state (reference reconcile_util.go
        filterByTainted): -> (untainted, migrate, lost, disconnecting,
        reconnecting, ignore_terminal)."""
        untainted, migrate, lost = [], [], []
        disconnecting, reconnecting = [], []
        supports_disconnect = tg.max_client_disconnect_s is not None
        for a in allocs:
            node = self.tainted.get(a.node_id)
            if a.client_status == AllocClientStatus.UNKNOWN:
                if node is None or getattr(node, "status", "") == "ready":
                    reconnecting.append(a)
                    continue
                if getattr(node, "status", "") == "disconnected":
                    expires = getattr(a, "disconnected_at", 0.0) + \
                        (tg.max_client_disconnect_s or 0.0)
                    if getattr(a, "disconnected_at", 0.0) and \
                            self.now >= expires:
                        # max_client_disconnect elapsed (this pass is the
                        # MAX_DISCONNECT_TIMEOUT follow-up eval): the
                        # alloc is lost and a replacement must place
                        lost.append(a)
                    else:
                        untainted.append(a)   # still unknown; wait
                    continue
                # node is down: unknown -> lost below
            if node is None:
                untainted.append(a)
                continue
            status = getattr(node, "status", "down")
            draining = getattr(node, "draining", False)
            if a.terminal_status():
                untainted.append(a)
                continue
            if status == "disconnected" and supports_disconnect:
                disconnecting.append(a)
            elif status in ("down", "disconnected"):
                # node state beats drain state: a node hard-killed
                # mid-drain has lost its allocs — routing them through
                # migrate (or leaving them untainted awaiting a migrate
                # slot) would strand them behind a drainer that can no
                # longer talk to the node
                lost.append(a)
            elif draining:
                if a.desired_transition.should_migrate():
                    migrate.append(a)
                else:
                    untainted.append(a)
            else:
                untainted.append(a)
        return untainted, migrate, lost, disconnecting, reconnecting

    def _compute_group(self, tg: TaskGroup, all_allocs: List[Allocation]) -> bool:
        res = self.results
        upd = res.tg_update(tg.name)
        is_service = not self.batch

        # batch jobs ignore successfully-completed allocs entirely
        live: List[Allocation] = []
        terminal: List[Allocation] = []
        for a in all_allocs:
            if a.terminal_status():
                terminal.append(a)
            else:
                live.append(a)

        untainted, migrate, lost, disconnecting, reconnecting = \
            self._filter_by_tainted(live, tg)

        # --- disconnecting -> mark unknown, schedule timeout followup
        for a in disconnecting:
            u = a.copy()
            u.client_status = AllocClientStatus.UNKNOWN
            u.desired_description = ALLOC_UNKNOWN
            u.disconnected_at = self.now
            timeout_eval = Evaluation(
                id=generate_uuid(), namespace=a.namespace, priority=self.eval_priority,
                type=self.job.type, triggered_by=EvalTrigger.MAX_DISCONNECT_TIMEOUT,
                job_id=self.job_id, status=EvalStatus.PENDING,
                wait_until=self.now + (tg.max_client_disconnect_s or 0.0))
            res.desired_followup_evals.setdefault(tg.name, []).append(timeout_eval)
            u.followup_eval_id = timeout_eval.id
            res.disconnect_updates[a.id] = u

        # --- reconnecting -> keep newest; stop failed/replaced duplicates
        for a in reconnecting:
            if a.client_status == AllocClientStatus.FAILED:
                res.stop.append(StopRequest(a, ALLOC_RESCHEDULED))
                upd["stop"] += 1
            else:
                u = a.copy()
                u.client_status = AllocClientStatus.RUNNING
                u.disconnected_at = 0.0
                res.reconnect_updates[a.id] = u
                untainted.append(a)

        # --- lost allocations stop with client status lost
        for a in lost:
            res.stop.append(StopRequest(
                a, ALLOC_LOST, client_status=AllocClientStatus.LOST))
            upd["stop"] += 1

        # --- rescheduling of failed allocs
        reschedule_now: List[Allocation] = []
        reschedule_later: List[Tuple[Allocation, float]] = []
        policy = tg.reschedule_policy
        still_untainted = []
        for a in untainted:
            if (a.client_status == AllocClientStatus.FAILED
                    or a.desired_transition.should_force_reschedule()):
                ok, wait_until = should_reschedule_now(a, policy, self.now, self.batch)
                if ok and wait_until == 0.0:
                    reschedule_now.append(a)
                    continue
                if ok:
                    reschedule_later.append((a, wait_until))
            still_untainted.append(a)
        untainted = still_untainted

        # client-terminal failed allocs (desired run, not yet replaced) are
        # reschedule candidates for both service and batch
        for a in terminal:
            if (a.client_status == AllocClientStatus.FAILED
                    and a.desired_status == AllocDesiredStatus.RUN
                    and not a.next_allocation and not a.followup_eval_id
                    and a.node_id not in self.tainted):
                ok, wait_until = should_reschedule_now(a, policy, self.now, self.batch)
                if ok and wait_until == 0.0:
                    reschedule_now.append(a)
                elif ok:
                    reschedule_later.append((a, wait_until))

        # --- delayed reschedule followup evals
        for a, wait_until in reschedule_later:
            ev = Evaluation(
                id=generate_uuid(), namespace=a.namespace,
                priority=self.eval_priority, type=self.job.type,
                triggered_by=EvalTrigger.RETRY_FAILED_ALLOC, job_id=self.job_id,
                status=EvalStatus.PENDING, wait_until=wait_until)
            res.desired_followup_evals.setdefault(tg.name, []).append(ev)
            u = a.copy()
            u.followup_eval_id = ev.id
            res.attribute_updates[a.id] = u
            upd["ignore"] += 1

        # --- canary bookkeeping
        canaries = [a for a in untainted if a.is_canary()]
        dstate = (self.deployment.task_groups.get(tg.name)
                  if self.deployment else None)
        requires_canaries = (
            is_service and tg.update is not None and tg.update.canary > 0
            and (dstate is None or not dstate.promoted)
            and any(a.job and a.job.version != self.job.version for a in untainted))
        promoted = bool(dstate and dstate.promoted)

        if promoted:
            # after promotion, non-canary old-version allocs are replaced
            # below; canaries become regular allocs
            canaries = []

        # --- split current vs old job version
        current_version, old_version = [], []
        for a in untainted:
            if a in reschedule_now:
                continue
            same = (a.job is not None and a.job.version == self.job.version
                    and not tasks_updated(
                        _group_of(a.job, tg.name) or tg, tg))
            (current_version if same else old_version).append(a)

        # in-place-updatable old-version allocs
        inplace, destructive = [], []
        for a in old_version:
            old_tg = _group_of(a.job, tg.name) if a.job else None
            if old_tg is not None and not tasks_updated(old_tg, tg):
                inplace.append(a)
            else:
                destructive.append(a)

        inplace_copies = []
        for a in inplace:
            u = a.copy()
            u.job = self.job
            res.inplace_update.append(u)
            inplace_copies.append(u)
            upd["in_place_update"] += 1
        current_version += inplace

        # --- duplicate names: two live allocs holding the same index
        # (racing plans under node churn can both place the same name)
        # leave the group permanently wedged — live == count means no
        # surplus stop, and slots_left == 0 means a lost sibling is never
        # replaced.  Stop every holder but one; keep a current-version,
        # healthy, newest alloc by preference (the reference computeStop
        # stops duplicate-name allocs before anything else).
        by_index: Dict[int, List[Allocation]] = {}
        for a in current_version + destructive:
            idx = a.index()
            if idx >= 0:
                by_index.setdefault(idx, []).append(a)
        for dupes in by_index.values():
            if len(dupes) <= 1:
                continue
            dupes.sort(key=lambda a: (a in current_version, a.is_healthy(),
                                      a.create_index, a.id), reverse=True)
            for a in dupes[1:]:
                res.stop.append(StopRequest(a, ALLOC_DUPLICATE))
                if a in destructive:
                    destructive.remove(a)
                else:
                    current_version.remove(a)
                for u in inplace_copies:
                    if u.id == a.id:
                        inplace_copies.remove(u)
                        res.inplace_update.remove(u)
                        upd["in_place_update"] -= 1
                        break
                upd["stop"] += 1

        # --- canary placements for updates
        want_canaries = 0
        if requires_canaries and destructive and not self.deployment_paused \
                and not self.deployment_failed:
            placed_canaries = len(canaries)
            want_canaries = max(tg.update.canary - placed_canaries, 0)

        # --- figure out how many we need
        count = tg.count
        have_names: Set[int] = set()
        for a in current_version + destructive + migrate + canaries:
            idx = a.index()
            if idx >= 0:
                have_names.add(idx)

        total_have = len(current_version) + len(destructive)
        # migrations: stop + replacement placement (drain follow-ups are the
        # drainer's job, not the reconciler's)
        for a in migrate:
            res.stop.append(StopRequest(a, ALLOC_MIGRATING))
            res.place.append(PlacementRequest(
                task_group=tg.name, name=a.name, previous_alloc=a,
                min_job_version=self.job.version))
            upd["migrate"] += 1

        # replacements for lost allocs, bounded by the group count (a lost
        # alloc past a scale-down must not resurrect).  A lost CANARY is
        # excluded: it is re-placed through the canary path below
        # (want_canaries counts only surviving canaries), so a generic
        # replacement here would double-place it and burn a count slot.
        lost_countable = [a for a in lost if not a.is_canary()]
        slots_left = max(0, count - total_have - len(migrate) - len(reschedule_now))
        lost_replaced = lost_countable[:slots_left]
        for a in lost_replaced:
            res.place.append(PlacementRequest(
                task_group=tg.name, name=a.name, previous_alloc=a))
            upd["place"] += 1

        # reschedule placements
        for a in reschedule_now:
            res.place.append(PlacementRequest(
                task_group=tg.name, name=a.name, previous_alloc=a,
                is_rescheduling=True))
            if not a.terminal_status():
                res.stop.append(StopRequest(a, ALLOC_RESCHEDULED))
            upd["place"] += 1

        # lost / rescheduled replacements reuse their predecessor's name:
        # those indexes are taken, and the scale-up and canary naming
        # below must not hand them out again (a storm that loses a node
        # mid-canary otherwise names the canary after a lost alloc's
        # in-flight replacement — two live allocs, one name)
        for a in lost_replaced + reschedule_now:
            idx = a.index()
            if idx >= 0:
                have_names.add(idx)

        # scale up: new placements for missing names (replacements for
        # migrating / lost / rescheduled allocs already hold their names)
        missing = count - (total_have + len(migrate) + len(lost_replaced)
                           + len(reschedule_now))
        if missing > 0:
            free_idx = (i for i in range(count + missing + len(have_names))
                        if i not in have_names)
            for _ in range(missing):
                idx = next(free_idx)
                have_names.add(idx)
                res.place.append(PlacementRequest(
                    task_group=tg.name,
                    name=alloc_name(self.job_id, tg.name, idx)))
                upd["place"] += 1

        # scale down: stop surplus (highest indices first, reference
        # computeStop removes from the end of the name space)
        surplus = total_have + len(migrate) - count
        if surplus > 0:
            candidates = sorted(current_version + destructive,
                                key=lambda a: (a.index(), a.id), reverse=True)
            for a in candidates[:surplus]:
                res.stop.append(StopRequest(a, ALLOC_NOT_NEEDED))
                if a in destructive:
                    destructive.remove(a)
                else:
                    current_version.remove(a)
                upd["stop"] += 1

        # --- canaries: place up to want_canaries; don't touch destructive yet
        if want_canaries > 0:
            for i in range(want_canaries):
                res.place.append(PlacementRequest(
                    task_group=tg.name,
                    name=alloc_name(self.job_id, tg.name, _next_free(have_names)),
                    is_canary=True))
                upd["canary"] += 1
            # unpromoted canaries pending: no destructive updates yet
            destructive_allowed = 0
        elif requires_canaries and not promoted:
            destructive_allowed = 0
        else:
            if is_service and tg.update:
                # rolling pace: max_parallel minus in-flight not-yet-healthy
                # replacements of the current version
                in_flight = sum(
                    1 for a in current_version
                    if a.job is not None and a.job.version == self.job.version
                    and not a.terminal_status() and not a.is_healthy())
                limit = max(0, tg.update.max_parallel - in_flight)
            else:
                limit = len(destructive)
            if self.deployment_paused or self.deployment_failed:
                limit = 0
            destructive_allowed = min(limit, len(destructive))

        # --- destructive updates under max_parallel
        for a in destructive[:destructive_allowed]:
            res.destructive_stop.append(StopRequest(a, ALLOC_NOT_NEEDED))
            res.place.append(PlacementRequest(
                task_group=tg.name, name=a.name, previous_alloc=a,
                is_destructive=True, min_job_version=self.job.version))
            upd["destructive_update"] += 1
        upd["ignore"] += len(current_version) + max(
            len(destructive) - destructive_allowed, 0)

        # --- deployment bookkeeping.  hadRunning (reference
        # reconcile.go computeGroup): a deployment is also created the
        # first time a job version places allocs — not only for
        # destructive updates — so initial registrations of service jobs
        # with an update stanza are health-gated too.
        had_current = any(
            a.job is not None and a.job.version == self.job.version
            and a.job.create_index == self.job.create_index
            for a in all_allocs)
        if is_service and tg.update is not None:
            self._ensure_deployment_state(tg, destructive, want_canaries,
                                          count, had_current)
            # in-place updates join the new deployment and re-prove
            # health (reference allocUpdateFnInplace sets DeploymentID;
            # the client's health tracker re-arms on the change) —
            # without this the watcher counts them as never-healthy and
            # fails a healthy rollout at the progress deadline
            d = res.deployment or self.deployment
            if (d is not None and d.job_version == self.job.version
                    and not self.deployment_failed
                    and not self.deployment_paused
                    and tg.name in d.task_groups):
                for u in inplace_copies:
                    if u.deployment_id != d.id:
                        u.deployment_id = d.id
                        u.deployment_status = None
                # current-version allocs outside the deployment join it
                # too: a lost-alloc replacement placed from a snapshot
                # predating the deployment carries no deployment_id, and
                # the watcher would wait on its health forever (the
                # rollout wedges RUNNING until the progress deadline)
                inplace_ids = {u.id for u in inplace_copies}
                for a in current_version:
                    if a.deployment_id != d.id and not a.is_canary() \
                            and a.id not in inplace_ids \
                            and a.id not in res.attribute_updates:
                        u = a.copy()
                        u.deployment_id = d.id
                        u.deployment_status = None
                        res.attribute_updates[a.id] = u

        # group is deployment-complete when nothing is pending
        complete = not destructive and not want_canaries and missing <= 0 \
            and not migrate and not reschedule_now
        return complete

    # -------------------------------------------------------- deployments

    def _ensure_deployment_state(self, tg: TaskGroup, destructive, want_canaries,
                                 count, had_current: bool) -> None:
        if self.deployment_failed or self.deployment_paused:
            return
        needs = bool(destructive or want_canaries or not had_current)
        d = self.results.deployment or self.deployment
        if d is None:
            if not needs or count == 0:
                return
            d = Deployment(
                namespace=self.job.namespace, job_id=self.job_id,
                job_version=self.job.version,
                job_modify_index=self.job.job_modify_index,
                job_create_index=self.job.create_index,
                is_multiregion=self.job.multiregion is not None,
                status=DeploymentStatus.RUNNING,
                status_description=DeploymentStatus.DESC_RUNNING,
                eval_priority=self.eval_priority)
            self.results.deployment = d
        if d.job_version != self.job.version:
            return
        if tg.name not in d.task_groups:
            u = tg.update
            d.task_groups[tg.name] = DeploymentState(
                auto_revert=u.auto_revert, auto_promote=u.auto_promote,
                desired_canaries=u.canary if want_canaries else 0,
                desired_total=count,
                progress_deadline_s=u.progress_deadline_s,
                require_progress_by=self.now + u.progress_deadline_s)

    def _finalize_deployment(self, deployment_complete: bool) -> None:
        d = self.deployment
        if d is None or not deployment_complete:
            return
        # isDeploymentComplete (reference reconcile.go): structural
        # completeness is not enough — every group must have reached its
        # desired healthy count, else success is the watcher's call later.
        if any(s.healthy_allocs < s.desired_total
               for s in d.task_groups.values()):
            return
        if d.status == DeploymentStatus.RUNNING and not d.requires_promotion():
            self.results.deployment_updates.append({
                "deployment_id": d.id,
                "status": DeploymentStatus.SUCCESSFUL,
                "description": DeploymentStatus.DESC_SUCCESSFUL})


def _group_of(job: Optional[Job], name: str) -> Optional[TaskGroup]:
    if job is None:
        return None
    return job.lookup_task_group(name)


def _next_free(have: Set[int]) -> int:
    i = 0
    while i in have:
        i += 1
    have.add(i)
    return i
