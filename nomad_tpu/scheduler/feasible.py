"""Feasibility: constraint programs over the dense attribute columns.

Host twin of the device constraint kernel; semantics mirror
scheduler/feasible.go:740-940 (resolveTarget/checkConstraint and the
operator table at :806-841).  Every function returns a bool[N] mask over
ClusterMatrix rows — vectorized numpy over hash/ordinal code columns for
=, !=, <, <=, >, >=, is_set; regex/version/semver/set_contains evaluate a
Python predicate over *distinct* values only and scatter (the analog of the
reference's "escaped" constraint fallback, context.go:252-420).
"""
from __future__ import annotations

import re
from functools import lru_cache
from typing import List, Optional

import numpy as np

from nomad_tpu.encode.attrs import AttrTable, hash_code
from nomad_tpu.encode.matrixizer import ClusterMatrix
from nomad_tpu.structs.job import Constraint, Operand
from nomad_tpu.scheduler.version import version_matches


@lru_cache(maxsize=4096)
def _compiled_regex(pattern: str) -> Optional["re.Pattern"]:
    try:
        return re.compile(pattern)
    except re.error:
        return None


def _set_contains_all(lval: str, rval: str) -> bool:
    have = {s.strip() for s in lval.split(",")}
    return all(s.strip() in have for s in rval.split(","))


def _set_contains_any(lval: str, rval: str) -> bool:
    have = {s.strip() for s in lval.split(",")}
    return any(s.strip() in have for s in rval.split(","))


def _ordered_mask(col, op: str, literal: str) -> np.ndarray:
    """Lexical <,<=,>,>= against a literal via ordinal codes
    (checkLexicalOrder semantics: plain string comparison)."""
    ords = col.ordinals()
    i, exact = col.ordinal_of(literal)
    found = ords >= 0
    if op == Operand.LT:
        return found & (ords < i)
    if op == Operand.LTE:
        return found & ((ords < i) | (exact & (ords == i)))
    if op == Operand.GT:
        return found & ((ords > i) if exact else (ords >= i))
    if op == Operand.GTE:
        return found & (ords >= i)
    raise ValueError(op)


_MISSING = object()   # a referenced column that no node materializes


def _resolve_side(cm: ClusterMatrix, target: str):
    """-> (column | None, literal | None, missing: bool).  Mirrors
    resolveTarget (feasible.go:769-802): non-interpolated targets are
    literals; unresolvable or never-seen columns are 'missing' (nil)."""
    col_name = AttrTable.target_to_column(target)
    if col_name is None:
        return None, target, False
    if col_name == "__unresolvable__":
        return None, None, True
    col = cm.attrs.columns.get(col_name)
    if col is None:
        return None, None, True
    return col, None, False


def constraint_mask(cm: ClusterMatrix, c: Constraint) -> np.ndarray:
    """bool[N] satisfaction mask for one constraint over all rows."""
    n = cm.n_rows
    op = c.operand
    # equality aliases (reference checkConstraint, feasible.go:808-814:
    # "=", "==" and "is" are one operator; "!=" and "not" likewise)
    if op in ("==", "is"):
        op = Operand.EQ
    elif op == "not":
        op = Operand.NEQ

    # distinct_hosts / distinct_property are not node-static; handled by the
    # stack against proposed allocations (checkConstraint returns true here,
    # feasible.go:809-813)
    if op in (Operand.DISTINCT_HOSTS, Operand.DISTINCT_PROPERTY):
        return np.ones(n, dtype=bool)

    lcol, llit, lmissing = _resolve_side(cm, c.ltarget)
    rcol, rlit, rmissing = _resolve_side(cm, c.rtarget)

    # ---- a side is nil on every row: collapse to a scalar per-row check
    if lmissing or rmissing:
        if lmissing and rmissing:
            return np.full(n, _scalar_check(op, None, None), dtype=bool)
        col, lit, col_is_lhs = (rcol, rlit, False) if lmissing else (lcol, llit, True)
        if col is None:
            v = lit
            res = _scalar_check(op, v, None) if col_is_lhs else _scalar_check(op, None, v)
            return np.full(n, res, dtype=bool)
        vals = col.values
        if col_is_lhs:
            return np.array([_scalar_check(op, v, None) for v in vals], dtype=bool)
        return np.array([_scalar_check(op, None, v) for v in vals], dtype=bool)

    # ---- both literals: scalar result broadcast
    if lcol is None and rcol is None:
        return np.full(n, _scalar_check(op, llit, rlit), dtype=bool)

    # ---- column vs column (rare): compare decoded values row-wise
    if lcol is not None and rcol is not None:
        lv, rv = lcol.values, rcol.values
        return np.array([_scalar_check(op, lv[i], rv[i]) for i in range(n)],
                        dtype=bool)

    # ---- column vs literal (the common case)
    swapped = lcol is None               # literal on the left, column right
    col = rcol if swapped else lcol
    lit = llit if swapped else rlit
    if swapped and op in (Operand.LT, Operand.LTE, Operand.GT, Operand.GTE):
        op = {Operand.LT: Operand.GT, Operand.LTE: Operand.GTE,
              Operand.GT: Operand.LT, Operand.GTE: Operand.LTE}[op]

    found = col.hash_codes != 0
    if op == Operand.EQ:
        return found & (col.hash_codes == hash_code(lit))
    if op == Operand.NEQ:
        # no found requirement: nil != literal is true (feasible.go:822)
        return col.hash_codes != hash_code(lit)
    if op in (Operand.LT, Operand.LTE, Operand.GT, Operand.GTE):
        return _ordered_mask(col, op, lit)
    if op == Operand.ATTRIBUTE_IS_SET:
        return found.copy()
    if op == Operand.ATTRIBUTE_IS_NOT_SET:
        return ~found
    # For the host-escape operators the *semantic* lhs/rhs matters: lVal is
    # the subject (version string / haystack), rVal the spec (constraint /
    # pattern / needle list) — checkConstraint (feasible.go:828-838).
    if op == Operand.VERSION:
        if swapped:   # literal is the version, column holds the spec
            return col.host_mask(lambda spec: version_matches(lit, spec))
        return col.host_mask(lambda v: version_matches(v, lit))
    if op == Operand.SEMVER:
        if swapped:
            return col.host_mask(lambda spec: version_matches(lit, spec, semver=True))
        return col.host_mask(lambda v: version_matches(v, lit, semver=True))
    if op == Operand.REGEX:
        if swapped:   # column holds the pattern, literal is the subject
            return col.host_mask(
                lambda pat: (rx := _compiled_regex(pat)) is not None
                and rx.search(lit) is not None)
        rx = _compiled_regex(lit)
        return col.host_mask(lambda v: rx is not None and rx.search(v) is not None)
    if op in (Operand.SET_CONTAINS, Operand.SET_CONTAINS_ALL):
        if swapped:
            return col.host_mask(lambda v: _set_contains_all(lit, v))
        return col.host_mask(lambda v: _set_contains_all(v, lit))
    if op == Operand.SET_CONTAINS_ANY:
        if swapped:
            return col.host_mask(lambda v: _set_contains_any(lit, v))
        return col.host_mask(lambda v: _set_contains_any(v, lit))
    return np.zeros(n, dtype=bool)   # unknown operator -> infeasible


def _scalar_check(op: str, lval: Optional[str], rval: Optional[str]) -> bool:
    lfound, rfound = lval is not None, rval is not None
    if op in ("=", "==", "is", Operand.EQ):
        return lfound and rfound and lval == rval
    if op in ("!=", "not", Operand.NEQ):
        return lval != rval
    if op in (Operand.LT, Operand.LTE, Operand.GT, Operand.GTE):
        if not (lfound and rfound):
            return False
        return {"<": lval < rval, "<=": lval <= rval,
                ">": lval > rval, ">=": lval >= rval}[op]
    if op == Operand.ATTRIBUTE_IS_SET:
        return lfound
    if op == Operand.ATTRIBUTE_IS_NOT_SET:
        return not lfound
    if op == Operand.VERSION:
        return lfound and rfound and version_matches(lval, rval)
    if op == Operand.SEMVER:
        return lfound and rfound and version_matches(lval, rval, semver=True)
    if op == Operand.REGEX:
        rx = _compiled_regex(rval) if rfound else None
        return lfound and rx is not None and rx.search(lval) is not None
    if op in (Operand.SET_CONTAINS, Operand.SET_CONTAINS_ALL):
        return lfound and rfound and _set_contains_all(lval, rval)
    if op == Operand.SET_CONTAINS_ANY:
        return lfound and rfound and _set_contains_any(lval, rval)
    return False


def constraints_mask(cm: ClusterMatrix, constraints: List[Constraint]) -> np.ndarray:
    mask = np.ones(cm.n_rows, dtype=bool)
    for c in constraints:
        mask &= constraint_mask(cm, c)
    return mask


def driver_mask(cm: ClusterMatrix, drivers: List[str]) -> np.ndarray:
    """DriverChecker (feasible.go:452): node must have each driver detected
    and healthy — encoded as the attr.driver.<name> column being set."""
    mask = np.ones(cm.n_rows, dtype=bool)
    for d in drivers:
        col = cm.attrs.columns.get(f"attr.driver.{d}")
        mask &= (col.hash_codes != 0) if col is not None else False
    return mask


def csi_volume_mask(cm: ClusterMatrix, snapshot, namespace: str,
                    job_id: str, volumes) -> np.ndarray:
    """CSIVolumeChecker (feasible.go:212-358), dense: the volume-level
    gates (exists, schedulable, free claims — with the same-job
    write-claim exception) are scalars broadcast over the mask; the
    node-level gates (healthy node plugin, MaxVolumes) use the
    fingerprint column and one bulk claim-count pass."""
    reqs = [r for r in volumes.values() if r.type == "csi"]
    if not reqs:
        return np.ones(cm.n_rows, dtype=bool)
    if snapshot is None:
        return np.zeros(cm.n_rows, dtype=bool)
    mask = np.ones(cm.n_rows, dtype=bool)
    counts = snapshot._store.csi_volume_counts_by_node() \
        if hasattr(snapshot, "_store") else {}
    for req in reqs:
        vol = snapshot.csi_volume_by_id(namespace, req.source)
        if vol is None:
            return np.zeros(cm.n_rows, dtype=bool)
        if req.read_only:
            if not (vol.read_schedulable() and vol.has_free_read_claims()):
                return np.zeros(cm.n_rows, dtype=bool)
        else:
            if not vol.write_schedulable():
                return np.zeros(cm.n_rows, dtype=bool)
            if not vol.has_free_write_claims():
                # blocking write claims owned by this very job are fine
                # (feasible.go:336-358); GC'd or foreign claims block
                for alloc_id in vol.write_claims:
                    a = snapshot.allocs.get(alloc_id) \
                        if hasattr(snapshot, "allocs") else None
                    if a is None or a.namespace != namespace \
                            or a.job_id != job_id:
                        return np.zeros(cm.n_rows, dtype=bool)
        # node plugin healthy (fingerprint column)
        col = cm.attrs.columns.get(f"csiplugin.{vol.plugin_id}")
        if col is None:
            return np.zeros(cm.n_rows, dtype=bool)
        mask &= col.hash_codes == hash_code("1")
        # MaxVolumes per node plugin
        plug = snapshot.csi_plugin_by_id(vol.plugin_id)
        if plug is not None:
            for node_id, row in cm.row_of.items():
                info = plug.nodes.get(node_id)
                if info is None:
                    continue
                maxv = info.get("max_volumes", 0)
                if maxv and counts.get(node_id, {}).get(
                        vol.plugin_id, 0) >= maxv:
                    mask[row] = False
    return mask


def device_place_cap(cm: ClusterMatrix, requests) -> np.ndarray:
    """i32[N]: how many instances of this group an eval may place per
    node = min over requests of floor(free_instances / count), counting
    committed usage plus the engine's in-flight overlay."""
    cap = np.full(cm.n_rows, 2**30, np.int64)
    from nomad_tpu.parallel.engine import get_engine
    eng = get_engine()
    for req in requests:
        best = np.zeros(cm.n_rows, np.int64)
        parts = req.name.split("/")
        for gid, caps in cm.device_caps.items():
            vendor, dtype, name = gid.split("/")
            if len(parts) == 1:
                match = parts[0] == dtype
            elif len(parts) == 2:
                match = parts[0] == dtype and parts[1] == name
            else:
                match = ((vendor, dtype, name) == tuple(parts))
            if not match:
                continue
            free = caps.astype(np.int64) - cm.device_used.get(gid, 0)
            if eng is not None:
                inflight = eng.device_overlay(cm, gid)
                if inflight is not None and \
                        inflight.shape[0] == free.shape[0]:
                    free = free - inflight
            best = np.maximum(best, free // max(req.count, 1))
        cap = np.minimum(cap, best)
    return np.clip(cap, 0, 2**30).astype(np.int32)


def host_volume_mask(cm: ClusterMatrix, volumes) -> np.ndarray:
    """HostVolumeChecker (feasible.go:133): every requested host volume must
    exist; a read-only node volume only satisfies read-only requests."""
    mask = np.ones(cm.n_rows, dtype=bool)
    for req in volumes.values():
        if req.type != "host":
            continue
        col = cm.attrs.columns.get(f"hostvol.{req.source}")
        if col is None:
            return np.zeros(cm.n_rows, dtype=bool)
        present = col.hash_codes != 0
        if req.read_only:
            mask &= present
        else:
            mask &= col.hash_codes == hash_code("rw")
    return mask


def device_mask(cm: ClusterMatrix, requests,
                include_usage: bool = True) -> np.ndarray:
    """DeviceChecker count feasibility (feasible.go:1192): every device
    request must be satisfiable by some matching device group's capacity.
    Matching follows NodeDeviceResource.ID semantics (type / type/name /
    vendor/type/name)."""
    mask = np.ones(cm.n_rows, dtype=bool)
    for req in requests:
        ok = np.zeros(cm.n_rows, dtype=bool)
        parts = req.name.split("/")
        for gid, caps in cm.device_caps.items():
            vendor, dtype, name = gid.split("/")
            if len(parts) == 1:
                match = parts[0] == dtype
            elif len(parts) == 2:
                match = parts[0] == dtype and parts[1] == name
            else:
                match = ((vendor, dtype, name) == tuple(parts))
            if match:
                if include_usage:
                    free = caps - cm.device_used.get(gid, 0)
                    from nomad_tpu.parallel.engine import get_engine
                    eng = get_engine()
                    if eng is not None:
                        inflight = eng.device_overlay(cm, gid)
                        if inflight is not None \
                                and inflight.shape[0] == free.shape[0]:
                            free = free - inflight
                else:
                    free = caps
                ok |= free >= req.count
        mask &= ok
    return mask
