"""Host-side device accounting (reference: nomad/structs/devices.go
DeviceAccounter, scheduler/device.go AllocateDevice).

Used for the check-devices path of AllocsFit and for assigning device
instance IDs to placements.  The *scoring/feasibility* of device-constrained
placement is done densely on device (ops/feasibility.py); instance-ID
assignment is inherently host-side bookkeeping.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _collect_node_devices(node) -> Dict[str, Tuple[object, set]]:
    """device-group id -> (NodeDevice, set(free instance ids))."""
    out = {}
    for dev in node.node_resources.devices:
        out[dev.id] = (dev, set(dev.instance_ids))
    return out


def _used_instances(allocs) -> Dict[str, set]:
    used: Dict[str, set] = {}
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        for tr in alloc.allocated_resources.tasks.values():
            for d in tr.devices:
                gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                used.setdefault(gid, set()).update(d.get("device_ids", []))
    return used


def device_accounter_fits(node, allocs) -> bool:
    """True iff no device instance is claimed twice and all claimed
    instances exist on the node (reference DeviceAccounter.AddAllocs
    returning collision=false)."""
    groups = _collect_node_devices(node)
    claimed: Dict[str, set] = {}
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        for tr in alloc.allocated_resources.tasks.values():
            for d in tr.devices:
                gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                if gid not in groups:
                    return False
                have = groups[gid][1]
                got = claimed.setdefault(gid, set())
                for inst in d.get("device_ids", []):
                    if inst in got or inst not in have:
                        return False
                    got.add(inst)
    return True


def assign_device_instances(node, allocs, request,
                            extra_used=None) -> Optional[dict]:
    """Pick `request.count` free instance ids from a matching, constraint-
    satisfying device group (reference scheduler/device.go:32-131
    AllocateDevice).  Returns {vendor,type,name,device_ids} or None.
    `extra_used` ({group id -> set(instance ids)}) carries grants already
    made to other requests of the same in-flight allocation, so two tasks
    in one group never share an instance.  Constraint/affinity evaluation
    over device attributes is handled by the caller via
    nomad_tpu.scheduler.feasible.check_operand on dev.attributes.
    """
    import random as _random
    used = _used_instances(allocs)
    for gid, ids in (extra_used or {}).items():
        used.setdefault(gid, set()).update(ids)
    for dev in node.node_resources.devices:
        if not dev.matches(request.name):
            continue
        free = [i for i in dev.healthy_ids()
                if i not in used.get(dev.id, set())]
        if len(free) >= request.count:
            # random choice among free instances: concurrent evals that
            # cannot see each other's in-flight assignments would all
            # deterministically take the first-free ids and collide at
            # the applier; random picks make them disjoint with high
            # probability (the applier still enforces exclusivity)
            picked = _random.sample(free, request.count)
            return {"vendor": dev.vendor, "type": dev.type, "name": dev.name,
                    "device_ids": picked}
    return None
