"""DenseStack: compiles a job against the cluster mirror into PlaceInputs.

Dense analog of scheduler/stack.go (GenericStack/SystemStack): where the
reference wires an iterator chain per eval and pulls nodes through it, we
compile the job's constraints/affinities/spreads once into padded tensors
and hand them to ops.place.place_eval.  Job-level and task-group-level
checkers are merged exactly like the reference's FeasibilityWrapper
(feasible.go:1010-1174): job constraints apply to every group, task
constraints/drivers fold into their group.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.encode.attrs import AttrTable
from nomad_tpu.encode.matrixizer import (
    ClusterMatrix,
    NUM_RESOURCE_DIMS,
    RES_CPU,
    RES_DISK,
    RES_MEM,
    RES_NET,
    pad_to_bucket,
)
from nomad_tpu.ops.place import PlaceInputs, PlaceResult, place_eval
from nomad_tpu.scheduler import feasible as fz
from nomad_tpu.structs.job import Constraint, Job, Operand, Spread, TaskGroup
from nomad_tpu.structs.config import (
    SCHEDULER_ALGORITHM_SPREAD,
    SchedulerConfiguration,
)

IMPLICIT_TARGET = "*"   # reference scheduler/spread.go implicitTarget


def group_demand(tg: TaskGroup) -> np.ndarray:
    """f32[R] total resource demand of one instance of the group."""
    d = np.zeros(NUM_RESOURCE_DIMS, dtype=np.float32)
    for t in tg.tasks:
        d[RES_CPU] += t.resources.cpu
        d[RES_MEM] += t.resources.memory_mb
        d[RES_NET] += sum(n.mbits for n in t.resources.networks)
    d[RES_DISK] = tg.ephemeral_disk.size_mb
    d[RES_NET] += sum(n.mbits for n in tg.networks)
    return d


def group_static_ports(tg: TaskGroup) -> List[int]:
    ports: List[int] = []
    for net in tg.networks:
        ports.extend(p.value for p in net.reserved_ports)
    for t in tg.tasks:
        for net in t.resources.networks:
            ports.extend(p.value for p in net.reserved_ports)
    return ports


def group_dynamic_port_count(tg: TaskGroup) -> int:
    n = sum(len(net.dynamic_ports) for net in tg.networks)
    n += sum(len(net.dynamic_ports) for t in tg.tasks for net in t.resources.networks)
    return n


@dataclass
class CompiledGroup:
    """Per-task-group dense artifacts."""
    tg: TaskGroup
    feasible: np.ndarray          # bool[N] static part (no distinct_* yet)
    affinity: np.ndarray          # f32[N]
    has_affinity: bool
    demand: np.ndarray            # f32[R]
    spreads: List[Spread]
    distinct_hosts_job: bool
    distinct_hosts_tg: bool
    distinct_property: List[Tuple[str, int, bool]]  # (target, limit, job-level)
    # for port-aware preemption: the mask before port-availability filters,
    # and the static ports the group asks for
    feasible_pre_ports: Optional[np.ndarray] = None   # bool[N]
    static_ports: List[int] = field(default_factory=list)
    # nodes with device COUNT capacity but no free instances: preemption
    # targets for PreemptForDevice
    device_blocked: Optional[np.ndarray] = None       # bool[N]
    # per-node placement capacity for this eval (instances the group may
    # still place per node; -1 = unlimited)
    place_cap: Optional[np.ndarray] = None            # i32[N]
    # constraint-only feasibility (datacenter/constraints/driver/volumes,
    # no readiness or capacity): the class-constant verdict that keys
    # blocked-eval unblocking — a down node or exhausted device must not
    # mark its whole class permanently ineligible
    class_feasible: Optional[np.ndarray] = None       # bool[N]


class DenseStack:
    """Compiles one job against one ClusterMatrix generation."""

    def __init__(self, cm: ClusterMatrix, config: Optional[SchedulerConfiguration] = None,
                 snapshot=None):
        self.cm = cm
        self.config = config or SchedulerConfiguration()
        self.snapshot = snapshot   # state view for CSI volume/claim reads
        self.spread_algorithm = (
            self.config.effective_scheduler_algorithm() == SCHEDULER_ALGORITHM_SPREAD)

    # ------------------------------------------------------------- compile

    def compile_group(self, job: Job, tg: TaskGroup) -> CompiledGroup:
        cm = self.cm
        n = cm.n_rows
        mask = cm.ready.copy()
        mask &= cm.dc_mask(job.datacenters)

        # job-level vs group-level matters for distinct_* scoping
        # (feasible.go:566-620: job-level collides with any job alloc,
        # group-level only with allocs of the same group)
        job_constraints = list(job.constraints)
        tg_constraints = list(tg.constraints)
        drivers = []
        dev_reqs = []
        affinities = list(job.affinities) + list(tg.affinities)
        for t in tg.tasks:
            tg_constraints += list(t.constraints)
            affinities += list(t.affinities)
            drivers.append(t.driver)
            dev_reqs.extend(t.resources.devices)
        constraints = job_constraints + tg_constraints

        distinct_hosts_job = any(c.operand == Operand.DISTINCT_HOSTS
                                 for c in job_constraints)
        distinct_hosts_tg = any(c.operand == Operand.DISTINCT_HOSTS
                                for c in tg_constraints)
        distinct_property = [
            (c.ltarget, int(c.rtarget) if c.rtarget else 1, c in job_constraints)
            for c in constraints if c.operand == Operand.DISTINCT_PROPERTY]

        static = fz.constraints_mask(cm, constraints)
        static &= fz.driver_mask(cm, drivers)
        static &= fz.host_volume_mask(cm, tg.volumes)
        class_feasible = cm.dc_mask(job.datacenters) & static
        mask &= static
        if any(v.type == "csi" for v in tg.volumes.values()):
            mask &= fz.csi_volume_mask(cm, self.snapshot, job.namespace,
                                       job.id, tg.volumes)

        # device COUNT capacity gates feasibility (reference DeviceChecker,
        # feasible.go:1192); instance AVAILABILITY applies after the
        # preemption-eligibility snapshot so device preemption can still
        # target instance-exhausted nodes
        if dev_reqs:
            mask &= fz.device_mask(cm, dev_reqs, include_usage=False)
        feasible_pre_ports = mask.copy()
        device_blocked = None
        place_cap = None
        if dev_reqs:
            avail = fz.device_mask(cm, dev_reqs)
            device_blocked = mask & ~avail
            mask = mask & avail
            # per-node instance budget for this eval: the kernel's
            # place_cap carry stops it over-subscribing a node's free
            # instances within one eval (deviceAllocator free counts)
            place_cap = fz.device_place_cap(cm, dev_reqs)
        static_ports = group_static_ports(tg)
        if static_ports:
            mask &= cm.static_ports_free(static_ports)
        dyn = group_dynamic_port_count(tg)
        if dyn:
            mask &= cm.free_dynamic_ports() >= dyn

        # affinity score: sum(weight * match) / sum(|weight|), rank.go:722-749
        aff = np.zeros(n, dtype=np.float32)
        has_aff = bool(affinities)
        if has_aff:
            total_w = sum(abs(a.weight) for a in affinities) or 1.0
            for a in affinities:
                m = fz.constraint_mask(
                    cm, Constraint(a.ltarget, a.rtarget, a.operand))
                aff += a.weight * m.astype(np.float32)
            aff /= total_w

        spreads = list(tg.spreads) + list(job.spreads)
        return CompiledGroup(tg=tg, feasible=mask, affinity=aff,
                             has_affinity=has_aff, demand=group_demand(tg),
                             spreads=spreads,
                             distinct_hosts_job=distinct_hosts_job,
                             distinct_hosts_tg=distinct_hosts_tg,
                             distinct_property=distinct_property,
                             feasible_pre_ports=feasible_pre_ports,
                             static_ports=static_ports,
                             device_blocked=device_blocked,
                             place_cap=place_cap,
                             class_feasible=class_feasible)

    # ------------------------------------------------------------- assemble

    def build_inputs(
        self,
        job: Job,
        groups: Sequence[CompiledGroup],
        slots: Sequence[int],                      # tg index per placement slot
        allocs_by_tg: Dict[str, List],             # existing (non-terminal) job allocs
        penalty_nodes: Optional[Dict[str, set]] = None,   # tg name -> node ids
        used_override: Optional[np.ndarray] = None,
    ) -> PlaceInputs:
        cm = self.cm
        N = cm.n_rows
        G = len(groups)
        S = pad_to_bucket(max(len(slots), 1), minimum=1)
        R = NUM_RESOURCE_DIMS
        penalty_nodes = penalty_nodes or {}

        feas = np.zeros((G, N), bool)
        aff = np.zeros((G, N), np.float32)
        has_aff = np.zeros(G, bool)
        desired = np.ones(G, np.int32)
        penalty = np.zeros((G, N), bool)
        tg_count = np.zeros((G, N), np.int32)

        K = max([len(g.spreads) for g in groups] + [1])
        # distinct value space per (g, k): padded to the max across groups
        vidx_all, desired_all, targeted_all, wfrac_all, counts_all, active_all = \
            [], [], [], [], [], []
        Vmax = 1
        spread_specs = []
        for gi, g in enumerate(groups):
            per_k = []
            for sp in g.spreads:
                col_name = AttrTable.target_to_column(sp.attribute)
                col = cm.attrs.columns.get(col_name) if col_name and col_name != "__unresolvable__" else None
                values = col.distinct() if col is not None else []
                Vmax = max(Vmax, len(values))
                per_k.append((sp, col, values))
            spread_specs.append(per_k)

        vidx = np.full((G, K, N), 0, np.int32)
        sdesired = np.full((G, K, Vmax + 1), -1.0, np.float32)
        stargeted = np.zeros((G, K), bool)
        swfrac = np.zeros((G, K), np.float32)
        scounts = np.zeros((G, K, Vmax + 1), np.float32)
        sactive = np.zeros((G, K), bool)

        for gi, g in enumerate(groups):
            feas[gi] = g.feasible
            aff[gi] = g.affinity
            has_aff[gi] = g.has_affinity
            desired[gi] = max(g.tg.count, 1)
            for nid in penalty_nodes.get(g.tg.name, ()):  # reschedule penalties
                row = cm.row_of.get(nid)
                if row is not None:
                    penalty[gi, row] = True
            # existing co-placements for anti-affinity + spread counts
            existing = allocs_by_tg.get(g.tg.name, [])
            for a in existing:
                row = cm.row_of.get(a.node_id)
                if row is not None:
                    tg_count[gi, row] += 1
            # distinct_hosts: co-hosted nodes infeasible (feasible.go:523-620);
            # job-level collides with any job alloc, group-level with same group
            if g.distinct_hosts_job or g.distinct_hosts_tg:
                for tg_name, allocs in allocs_by_tg.items():
                    if not g.distinct_hosts_job and tg_name != g.tg.name:
                        continue
                    for a in allocs:
                        row = cm.row_of.get(a.node_id)
                        if row is not None:
                            feas[gi, row] = False
            # distinct_property: value counts >= limit infeasible (propertyset.go)
            for target, limit, job_level in g.distinct_property:
                col_name = AttrTable.target_to_column(target)
                col = cm.attrs.columns.get(col_name) if col_name else None
                if col is None:
                    continue
                counts: Dict[str, int] = {}
                for tg_name, allocs in allocs_by_tg.items():
                    if not job_level and tg_name != g.tg.name:
                        continue
                    for a in allocs:
                        row = cm.row_of.get(a.node_id)
                        if row is not None and col.values[row] is not None:
                            counts[col.values[row]] = counts.get(col.values[row], 0) + 1
                for row in range(N):
                    v = col.values[row]
                    if v is not None and counts.get(v, 0) >= limit:
                        feas[gi, row] = False

            sum_w = sum(sp.weight for sp, _, _ in spread_specs[gi]) or 1
            for ki, (sp, col, values) in enumerate(spread_specs[gi]):
                sactive[gi, ki] = True
                swfrac[gi, ki] = sp.weight / sum_w
                rank = {v: i for i, v in enumerate(values)}
                V = len(values)
                if col is not None:
                    vidx[gi, ki] = np.array(
                        [rank.get(v, Vmax) if v is not None else Vmax
                         for v in col.values], np.int32)
                else:
                    vidx[gi, ki] = Vmax
                if sp.targets:
                    stargeted[gi, ki] = True
                    total = max(g.tg.count, 1)
                    sum_desired = 0.0
                    for t in sp.targets:
                        dcount = (t.percent / 100.0) * total
                        if t.value in rank:
                            sdesired[gi, ki, rank[t.value]] = dcount
                        sum_desired += dcount
                    if 0 < sum_desired < total:
                        # implicit target: remaining count for untargeted values
                        rem = total - sum_desired
                        for v, i in rank.items():
                            if sdesired[gi, ki, i] < 0:
                                sdesired[gi, ki, i] = rem
                # initial counts from existing allocs of this tg
                if col is not None:
                    for a in allocs_by_tg.get(g.tg.name, []):
                        row = cm.row_of.get(a.node_id)
                        if row is not None and col.values[row] in rank:
                            scounts[gi, ki, rank[col.values[row]]] += 1

        place_cap = np.full((G, N), -1, np.int32)
        for gi, g in enumerate(groups):
            if g.place_cap is not None:
                place_cap[gi] = g.place_cap

        demand = np.zeros((S, R), np.float32)
        slot_tg = np.zeros(S, np.int32)
        slot_active = np.zeros(S, bool)
        for si, gi in enumerate(slots):
            demand[si] = groups[gi].demand
            slot_tg[si] = gi
            slot_active[si] = True

        used = used_override if used_override is not None else self.cm.used
        return PlaceInputs(
            capacity=np.ascontiguousarray(cm.capacity),
            used=np.ascontiguousarray(used.astype(np.float32)),
            feasible=feas, affinity=aff, has_affinity=has_aff,
            desired_count=desired, penalty=penalty, tg_count=tg_count,
            spread_vidx=vidx, spread_desired=sdesired, spread_targeted=stargeted,
            spread_wfrac=swfrac, spread_counts=scounts, spread_active=sactive,
            place_cap=place_cap,
            demand=demand, slot_tg=slot_tg, slot_active=slot_active,
        )

    def place(self, inputs: PlaceInputs, deltas=None) -> PlaceResult:
        """Run the placement kernel.  Routed through the process-wide
        PlacementEngine so concurrent evals coalesce into one device
        dispatch; `deltas` is the sparse (row, f32[R]) usage-adjustment
        list already applied to inputs.used (the engine re-applies it to a
        dispatch-time basis in the batched path).

        Sets `self.last_ticket`: the caller must hand it back to
        `engine.complete()` once the resulting plan is submitted (the
        generic scheduler does), releasing the in-flight usage overlay."""
        from nomad_tpu.parallel.engine import get_engine
        eng = get_engine()
        if eng is not None:
            result, self.last_ticket = eng.place(
                self.cm, inputs, deltas,
                spread_algorithm=self.spread_algorithm)
            return result
        self.last_ticket = None
        return place_eval(inputs, spread_algorithm=self.spread_algorithm)

    def release(self) -> None:
        """Release the in-flight usage contribution of the last place()."""
        ticket = getattr(self, "last_ticket", None)
        if ticket is not None:
            from nomad_tpu.parallel.engine import get_engine
            eng = get_engine()
            if eng is not None:
                eng.complete(ticket)
            self.last_ticket = None
