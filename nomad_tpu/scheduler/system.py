"""System / sysbatch schedulers (reference: scheduler/scheduler_system.go:27-527
and util.go diffSystemAllocsForNode:70).

One allocation per eligible node per task group.  Feasibility is one dense
mask over all nodes; the per-node resource check is a single vectorized
fits_after call — no placement coupling across nodes (each node hosts its
own instance), so no scan is needed.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.encode.matrixizer import comparable_vec

from nomad_tpu.scheduler.placement import PortClaims, build_allocation
from nomad_tpu.scheduler.reconcile import tasks_updated
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.scheduler.util import tainted_nodes
from nomad_tpu.structs import Allocation, AllocClientStatus, Evaluation, EvalStatus
from nomad_tpu.structs.alloc import AllocMetric, alloc_name
from nomad_tpu.structs.node import NodeStatus
from nomad_tpu.structs.plan import PlanResult


class SystemScheduler:
    sysbatch = False

    def __init__(self, state, planner):
        self.state = state
        self.planner = planner
        self.eval: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}
        self._preemptor = None

    def process(self, ev: Evaluation) -> None:
        self.eval = ev
        job = self.state.job_by_id(ev.namespace, ev.job_id)
        allocs = self.state.allocs_by_job(ev.namespace, ev.job_id)
        plan = ev.make_plan(job)
        cm = self.state.matrix

        live: Dict[Tuple[str, str], Allocation] = {}
        terminal_newest: Dict[Tuple[str, str], Allocation] = {}
        for a in allocs:
            key = (a.node_id, a.name)
            if a.terminal_status():
                prev = terminal_newest.get(key)
                if prev is None or prev.create_index < a.create_index:
                    terminal_newest[key] = a
            else:
                live[key] = a

        stopped = job is None or job.stopped()
        if stopped:
            for a in live.values():
                plan.append_stopped_alloc(a, "alloc not needed due to job being stopped")
            if not plan.is_no_op():
                self.planner.submit_plan(plan)
            ev.queued_allocations = {}
            return

        tainted = tainted_nodes(self.state, allocs)

        stack = DenseStack(cm, self.state.scheduler_config,
                           snapshot=self.state)
        groups = [stack.compile_group(job, tg) for tg in job.task_groups]
        used = cm.used.copy()
        ports = PortClaims(cm)
        now = _time.time()
        self.queued_allocs = {tg.name: 0 for tg in job.task_groups}

        # stops: down nodes -> lost; draining -> migrate-stop
        for key, a in list(live.items()):
            node = tainted.get(a.node_id)
            if a.node_id in tainted:
                if node is None or node.status in (NodeStatus.DOWN,
                                                   NodeStatus.DISCONNECTED):
                    plan.append_stopped_alloc(
                        a, "alloc was lost since its node is down",
                        client_status=AllocClientStatus.LOST)
                else:   # draining
                    plan.append_stopped_alloc(a, "alloc is being migrated")
                del live[key]
                row = cm.row_of.get(a.node_id)
                if row is not None:
                    cr = a.comparable_resources()
                    used[row] -= comparable_vec(cr)

        for gi, tg in enumerate(job.task_groups):
            g = groups[gi]
            name = alloc_name(job.id, tg.name, 0)
            feas = g.feasible
            d = g.demand
            for node_id, row in cm.row_of.items():
                if not feas[row]:
                    continue
                key = (node_id, name)
                cur = live.get(key)
                if cur is not None:
                    # update in place or destructively on job change
                    if cur.job is not None and cur.job.version != job.version:
                        old_tg = cur.job.lookup_task_group(tg.name)
                        if old_tg is not None and not tasks_updated(old_tg, tg):
                            u = cur.copy()
                            u.job = job
                            plan.append_alloc(u, job)
                        else:
                            plan.append_stopped_alloc(
                                cur, "alloc not needed due to job update")
                            cr = cur.comparable_resources()
                            used[row] -= comparable_vec(cr)
                            self._try_place(plan, job, tg, name, node_id, row,
                                            used, d, ports, now)
                    continue
                if self.sysbatch:
                    t = terminal_newest.get(key)
                    if t is not None and t.ran_successfully():
                        continue   # sysbatch doesn't rerun completed nodes
                elif terminal_newest.get(key) is not None and \
                        terminal_newest[key].client_status == AllocClientStatus.COMPLETE:
                    continue       # system alloc completed on purpose
                self._try_place(plan, job, tg, name, node_id, row, used, d,
                                ports, now)

        ev.queued_allocations = dict(self.queued_allocs)
        if not plan.is_no_op():
            self.planner.submit_plan(plan)

    def _try_place(self, plan, job, tg, name, node_id, row, used, d, ports, now):
        cm = self.state.matrix
        preempted = []
        if not np.all(used[row] + d <= cm.capacity[row]):
            preempted = self._try_preempt(plan, job, row, d, used)
            if preempted is None:
                m = self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
                m.exhausted_node(node_id, "resources")
                self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name, 0) + 1
                return
        node = self.state.node_by_id(node_id)
        metric = AllocMetric()
        metric.nodes_evaluated = 1
        alloc = build_allocation(
            job=job, tg=tg, name=name, node_id=node_id,
            node_name=node.name if node else "", eval_id=self.eval.id,
            row=row, ports=ports, freed_ports=set(), metric=metric, now=now)
        if alloc is None:
            m = self.failed_tg_allocs.setdefault(tg.name, AllocMetric())
            m.exhausted_node(node_id, "ports")
            return
        if preempted:
            alloc.preempted_allocations = [a.id for a in preempted]
            for a in preempted:
                plan.append_preempted_alloc(a, alloc.id)
                cr = a.comparable_resources()
                used[row] -= comparable_vec(cr)
        used[row] += d
        plan.append_alloc(alloc, None)

    def _try_preempt(self, plan, job, row, d, used):
        """System jobs preempt lower-priority work by default (reference
        SystemScheduler + PreemptionConfig.SystemSchedulerEnabled)."""
        if not self.state.scheduler_config.preemption_enabled(
                "sysbatch" if self.sysbatch else "system"):
            return None
        if self._preemptor is None:
            from nomad_tpu.scheduler.preemption import Preemptor
            self._preemptor = Preemptor(self.state, job.priority)
        feas = np.zeros(self.state.matrix.n_rows, bool)
        feas[row] = True
        found = self._preemptor.find(feas, d, used)
        if found is None:
            return None
        _, evicted = found
        self._preemptor.invalidate({a.id for a in evicted})
        return evicted


class SysBatchScheduler(SystemScheduler):
    sysbatch = True
