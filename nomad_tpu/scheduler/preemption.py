"""Host-side preemption orchestration around the dense kernel.

Reference: scheduler/preemption.go Preemptor.  The device kernel
(ops.preempt) answers met/picked for every node at once; this module
builds the padded candidate matrices from the snapshot, ranks the eligible
nodes (fit score after preemption + logistic preemption score, mirroring
PreemptionScoringIterator rank.go:817-868), and applies the reference's
final superset-filter pass (preemption.go:702-732) to the chosen node.

Network preemption (PreemptForNetwork, preemption.go:270-454): bandwidth
rides the RES_NET resource dimension, so the same greedy distance kernel
frees MBits; static-port conflicts are resolved here by force-evicting the
preemptible holders of the asked ports (ports held by non-preemptible
allocs make the node ineligible, mirroring filteredReservedPorts).

Device preemption (PreemptForDevice, preemption.go:472-555): per-node
instance-count preemption in preempt_for_device() — group matching allocs
by device group, take lowest-priority first until free+preempted instances
cover the ask.

Not yet modeled: per-job migrate max_parallel scoring penalty.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.encode.matrixizer import NUM_RESOURCE_DIMS, comparable_vec, pad_to_bucket
from nomad_tpu.ops.preempt import (
    net_priority,
    preempt_for_task_group_np,
    preemption_score,
)

PRIORITY_DELTA = 10   # preemption.go:663-697: need >= 10 priority gap


def _score_fit_np(capacity, util):
    """Numpy twin of ops.fit.score_fit (binpack) for the host ranking
    path — worker threads stay off the device."""
    from nomad_tpu.encode.matrixizer import RES_CPU, RES_MEM
    cap = capacity[:, (RES_CPU, RES_MEM)].astype(np.float64)
    use = util[:, (RES_CPU, RES_MEM)].astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = 1.0 - use / cap
    zero = cap <= 0.0
    frac = np.where(zero & (use > 0.0), -np.inf, frac)
    frac = np.where(zero & (use <= 0.0), 1.0, frac)
    total = np.power(10.0, frac).sum(axis=-1)
    return np.clip(20.0 - total, 0.0, 18.0).astype(np.float32)


class Preemptor:
    def __init__(self, snapshot, job_priority: int, seed: str = ""):
        self.snapshot = snapshot
        self.cm = snapshot.matrix
        self.job_priority = job_priority
        # per-eval decorrelation seed (the reference's seeded node shuffle,
        # util.go:464-486): concurrent evals must not all rank the same
        # victims first or only one plan per round survives the applier
        self._seed = seed
        self._built = False
        self.already_preempted: Set[str] = set()

    # ------------------------------------------------------------- build

    def _build(self) -> None:
        """Pad per-node preemptible-alloc matrices."""
        cm = self.cm
        N = cm.n_rows
        per_node: List[List] = [[] for _ in range(N)]
        for node_id, row in cm.row_of.items():
            for a in self.snapshot.allocs_by_node(node_id):
                if a.terminal_status():
                    continue
                prio = a.job.priority if a.job is not None else 50
                if self.job_priority - prio < PRIORITY_DELTA:
                    continue
                per_node[row].append(a)
        A = pad_to_bucket(max([len(x) for x in per_node] + [1]), minimum=4)
        self.cand_allocs = per_node
        self.cand_res = np.zeros((N, A, NUM_RESOURCE_DIMS), np.float32)
        self.cand_prio = np.zeros((N, A), np.int32)
        self.cand_valid = np.zeros((N, A), bool)
        self._cand_index = {}          # alloc id -> (row, i)
        for row, allocs in enumerate(per_node):
            for i, a in enumerate(allocs):
                cr = a.comparable_resources()
                self.cand_res[row, i] = comparable_vec(cr)
                self.cand_prio[row, i] = a.job.priority if a.job else 50
                self.cand_valid[row, i] = True
                self._cand_index[a.id] = (row, i)
        self.max_steps = min(A, 32)
        self._built = True

    def invalidate(self, alloc_ids: Set[str]) -> None:
        """Mark allocs chosen for preemption unusable for later slots."""
        if not self._built:
            return
        for aid in alloc_ids:
            loc = self._cand_index.get(aid)
            if loc is not None:
                self.cand_valid[loc[0], loc[1]] = False

    # ------------------------------------------------------------- ports

    def _port_forced_evictions(self, static_ports: List[int],
                               rows: np.ndarray):
        """For each port-conflicted row: which preemptible candidates hold
        the asked ports.  Returns {row: set(cand idx)} for eligible rows;
        rows where an asked port is held by a NON-preemptible alloc are
        excluded (reference filteredReservedPorts, preemption.go:290-323).
        """
        want = set(static_ports)
        out: Dict[int, Set[int]] = {}
        for row in rows:
            holders: Set[int] = set()
            eligible = True
            conflicted = {
                p for p in want
                if (self.cm.port_words[row, p >> 5] >> np.uint32(p & 31)) & 1}
            if not conflicted:
                continue
            cand_port_sets = [
                set(self.cm._alloc_ports(a)) for a in self.cand_allocs[row]]
            for p in conflicted:
                held_by = [i for i, ps in enumerate(cand_port_sets)
                           if p in ps and self.cand_valid[row, i]]
                if not held_by:
                    eligible = False   # a higher-priority alloc owns it
                    break
                holders.update(held_by)
            if eligible:
                out[int(row)] = holders
        return out

    # ------------------------------------------------------------- find

    def find(self, feasible: np.ndarray, demand: np.ndarray,
             used: np.ndarray,
             static_ports: Optional[List[int]] = None,
             feasible_pre_ports: Optional[np.ndarray] = None,
             device_blocked: Optional[np.ndarray] = None,
             ) -> Optional[Tuple[int, List]]:
        """-> (node row, allocs to preempt) or None.

        `used` is the eval's current proposed usage matrix; remaining =
        capacity - used per node.  When `static_ports` is given,
        `feasible_pre_ports` is the mask before the port-availability
        filter: port-conflicted nodes become eligible by force-evicting
        the preemptible holders of the asked ports."""
        if not self._built:
            self._build()
        cm = self.cm
        remaining = cm.capacity - used

        forced: Dict[int, Set[int]] = {}
        feasible = np.asarray(feasible).copy()
        if static_ports and feasible_pre_ports is not None:
            port_rows = np.flatnonzero(feasible_pre_ports & ~feasible)
            forced = self._port_forced_evictions(static_ports, port_rows)
            for row in forced:
                feasible[row] = True   # eligible again via eviction
        # instance-exhausted device nodes: eligible targets — the actual
        # device evictions are chosen later by preempt_for_device inside
        # the placement (PreemptForDevice, preemption.go:472)
        dev_rows = np.zeros(len(feasible), bool)
        if device_blocked is not None:
            dev_rows = np.asarray(device_blocked) & ~feasible
            feasible |= dev_rows

        met, picked, avail_after = preempt_for_task_group_np(
            self.cand_res, self.cand_prio, self.cand_valid,
            remaining.astype(np.float32), demand.astype(np.float32),
            max_steps=self.max_steps)
        met = np.asarray(met) & feasible
        # nodes that fit without eviction are not preemption targets --
        # unless a port eviction is what makes them usable
        fits_plain = np.all(remaining >= demand, axis=-1)
        no_ports_needed = np.array(
            [r not in forced for r in range(len(fits_plain))])
        met &= ~(fits_plain & no_ports_needed & ~dev_rows)
        # port/device rows that fit resource-wise still need their evictions
        met |= (np.array([r in forced for r in range(len(fits_plain))])
                & fits_plain & feasible)
        met |= dev_rows & fits_plain
        picked = np.asarray(picked).copy()
        # fold the forced port evictions into each row's pick set, and
        # re-check resource sufficiency with the combined freed set (the
        # kernel ran without knowing about the forced frees)
        for row, holders in forced.items():
            for i in holders:
                picked[row, i] = True
            freed = self.cand_res[row][picked[row]].sum(axis=0)
            met[row] = bool(np.all(remaining[row] + freed >= demand))
        if not met.any():
            return None

        # rank eligible nodes: mean of (binpack fit after preemption) and
        # the logistic preemption score of the evicted set.  Fit for ALL
        # nodes in one vectorized call — a per-row eager device op would
        # cost one host<->device round trip per node
        rows = np.flatnonzero(met)
        freed_all = (self.cand_res * picked[:, :, None]).sum(axis=1)
        util_after = used - freed_all + demand[None, :]
        fit_all = _score_fit_np(cm.capacity, util_after) / 18.0
        best_row, best_score = -1, -np.inf
        row_scores = []
        for row in rows:
            evicted = [self.cand_allocs[row][i]
                       for i in np.flatnonzero(picked[row])]
            p_score = preemption_score(net_priority(
                [a.job.priority if a.job else 50 for a in evicted]))
            score = (float(fit_all[row]) + p_score) / 2.0
            row_scores.append((score, int(row)))
            if score > best_score:
                best_score, best_row = score, int(row)
        # every met row, best-first, for find_many: eviction sets on
        # distinct rows are disjoint, so one kernel round can serve a
        # whole batch of failed slots instead of one
        row_scores.sort(reverse=True)
        self._last_ranked = [(row, picked, forced, remaining)
                             for _, row in row_scores]

        protected = {self.cand_allocs[best_row][i].id
                     for i in forced.get(best_row, ())}
        evicted = [self.cand_allocs[best_row][i]
                   for i in np.flatnonzero(picked[best_row])]
        evicted = self._superset_filter(
            evicted, remaining[best_row], demand, protected)
        return best_row, evicted

    def find_many(self, feasible: np.ndarray, demand: np.ndarray,
                  used: np.ndarray, count: int,
                  static_ports: Optional[List[int]] = None,
                  feasible_pre_ports: Optional[np.ndarray] = None,
                  device_blocked: Optional[np.ndarray] = None,
                  ) -> List[Tuple[int, List]]:
        """Up to `count` preemption assignments from ONE kernel round.
        Eviction sets on distinct rows are disjoint (an alloc lives on one
        node), so the round's ranked rows can serve `count` slots without
        paying one device round trip per slot; later rounds (triggered by
        the caller when this batch is exhausted) see updated usage and
        invalidated candidates."""
        first = self.find(feasible, demand, used,
                          static_ports=static_ports,
                          feasible_pre_ports=feasible_pre_ports,
                          device_blocked=device_blocked)
        if first is None:
            return []
        out: List[Tuple[int, List]] = [first]
        row0 = first[0]
        for row, picked, forced, remaining in getattr(
                self, "_last_ranked", []):
            if len(out) >= count:
                break
            if row == row0:
                continue
            evicted = [self.cand_allocs[row][i]
                       for i in np.flatnonzero(picked[row])
                       if self.cand_valid[row, i]]
            if not evicted:
                continue
            protected = {self.cand_allocs[row][i].id
                         for i in forced.get(row, ())}
            evicted = self._superset_filter(
                evicted, remaining[row], demand, protected)
            out.append((row, evicted))
        return out

    # ------------------------------------------------------------- devices

    def preempt_for_device(self, node, allocs, request,
                           exclude: Optional[Set[str]] = None
                           ) -> Optional[List]:
        """PreemptForDevice (preemption.go:472-555) for one node: find the
        lowest-priority allocs holding instances of a device group matching
        `request` so that free + preempted instances cover request.count.
        Returns the allocs to evict, or None."""
        exclude = exclude or set()
        from nomad_tpu.scheduler.devices import _used_instances

        live = [a for a in allocs
                if not a.terminal_status() and a.id not in exclude]
        used_by_group = _used_instances(live)   # gid -> set(instance ids)

        best: Optional[Tuple[int, List]] = None   # (net_priority, allocs)
        for dev in node.node_resources.devices:
            if not dev.matches(request.name):
                continue
            # per-alloc instance counts on this device group (deduped view
            # shared with assign_device_instances via _used_instances)
            holders: List[Tuple[object, int]] = []
            for a in live:
                n_inst = 0
                for tr in a.allocated_resources.tasks.values():
                    for d in tr.devices:
                        gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                        if gid == dev.id:
                            n_inst += len(d.get("device_ids", []))
                if n_inst == 0:
                    continue
                prio = a.job.priority if a.job is not None else 50
                if self.job_priority - prio < PRIORITY_DELTA:
                    continue
                holders.append((a, n_inst))
            free = len(dev.instance_ids) - len(used_by_group.get(dev.id, ()))
            if free >= request.count:
                return []          # no preemption needed on this group
            # lowest priority first into the option, then the reference's
            # refinement pass: sort picks by instance count descending and
            # keep only what's needed (selectBestAllocs, preemption.go:556+)
            holders.sort(key=lambda t: (
                t[0].job.priority if t[0].job else 50, t[1]))
            picked, got = [], free
            for a, n_inst in holders:
                picked.append((a, n_inst))
                got += n_inst
                if got >= request.count:
                    break
            if got < request.count:
                continue
            picked.sort(key=lambda t: -t[1])
            filtered, covered = [], free
            for a, n_inst in picked:
                if covered >= request.count:
                    break
                filtered.append(a)
                covered += n_inst
            # net priority = sum of UNIQUE priorities in the option
            # (selectBestAllocs, preemption.go:557-558); lowest wins
            prios = {p.job.priority if p.job else 50 for p in filtered}
            cand = (int(sum(prios)), filtered)
            if best is None or cand[0] < best[0]:
                best = cand
        return best[1] if best is not None else None

    # ------------------------------------------------------------- filter

    def _superset_filter(self, picks: List, remaining: np.ndarray,
                         ask: np.ndarray,
                         protected: Optional[Set[str]] = None) -> List:
        """Drop picks whose resources are already covered by the rest
        (reference filterSuperset: iterate largest-first, keep only while
        the remainder no longer satisfies the ask).  Allocs in `protected`
        (port holders) are never dropped."""
        protected = protected or set()

        def vec(a):
            cr = a.comparable_resources()
            return comparable_vec(cr)

        picks = sorted(picks, key=lambda a: -vec(a).sum())
        kept = list(picks)
        for a in picks:
            if a.id in protected:
                continue
            trial = [x for x in kept if x.id != a.id]
            avail = remaining + sum((vec(x) for x in trial),
                                    np.zeros(NUM_RESOURCE_DIMS, np.float32))
            if np.all(avail >= ask) and trial:
                kept = trial
        return kept
