"""Host-side preemption orchestration around the dense kernel.

Reference: scheduler/preemption.go Preemptor.  The device kernel
(ops.preempt) answers met/picked for every node at once; this module
builds the padded candidate matrices from the snapshot, ranks the eligible
nodes (fit score after preemption + logistic preemption score, mirroring
PreemptionScoringIterator rank.go:817-868), and applies the reference's
final superset-filter pass (preemption.go:702-732) to the chosen node.

Not yet modeled: per-job migrate max_parallel scoring penalty and the
network/device-bandwidth preemption variants (PreemptForNetwork/Device).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.encode.matrixizer import pad_to_bucket
from nomad_tpu.ops.preempt import (
    net_priority,
    preempt_for_task_group,
    preemption_score,
)

PRIORITY_DELTA = 10   # preemption.go:663-697: need >= 10 priority gap


class Preemptor:
    def __init__(self, snapshot, job_priority: int):
        self.snapshot = snapshot
        self.cm = snapshot.matrix
        self.job_priority = job_priority
        self._built = False
        self.already_preempted: Set[str] = set()

    # ------------------------------------------------------------- build

    def _build(self) -> None:
        """Pad per-node preemptible-alloc matrices."""
        cm = self.cm
        N = cm.n_rows
        per_node: List[List] = [[] for _ in range(N)]
        for node_id, row in cm.row_of.items():
            for a in self.snapshot.allocs_by_node(node_id):
                if a.terminal_status():
                    continue
                prio = a.job.priority if a.job is not None else 50
                if self.job_priority - prio < PRIORITY_DELTA:
                    continue
                per_node[row].append(a)
        A = pad_to_bucket(max([len(x) for x in per_node] + [1]), minimum=4)
        self.cand_allocs = per_node
        self.cand_res = np.zeros((N, A, 3), np.float32)
        self.cand_prio = np.zeros((N, A), np.int32)
        self.cand_valid = np.zeros((N, A), bool)
        for row, allocs in enumerate(per_node):
            for i, a in enumerate(allocs):
                cr = a.comparable_resources()
                self.cand_res[row, i] = (cr.cpu_shares, cr.memory_mb, cr.disk_mb)
                self.cand_prio[row, i] = a.job.priority if a.job else 50
                self.cand_valid[row, i] = True
        self.max_steps = min(A, 32)
        self._built = True

    def invalidate(self, alloc_ids: Set[str]) -> None:
        """Mark allocs chosen for preemption unusable for later slots."""
        if not self._built:
            return
        for row, allocs in enumerate(self.cand_allocs):
            for i, a in enumerate(allocs):
                if a.id in alloc_ids:
                    self.cand_valid[row, i] = False

    # ------------------------------------------------------------- find

    def find(self, feasible: np.ndarray, demand: np.ndarray,
             used: np.ndarray) -> Optional[Tuple[int, List]]:
        """-> (node row, allocs to preempt) or None.

        `used` is the eval's current proposed usage matrix; remaining =
        capacity - used per node."""
        if not self._built:
            self._build()
        cm = self.cm
        remaining = cm.capacity - used
        met, picked, avail_after = preempt_for_task_group(
            self.cand_res, self.cand_prio, self.cand_valid,
            remaining.astype(np.float32), demand.astype(np.float32),
            max_steps=self.max_steps)
        met = np.asarray(met) & feasible
        # nodes that fit without eviction are not preemption targets
        met &= ~np.all(remaining >= demand, axis=-1)
        if not met.any():
            return None
        picked = np.asarray(picked)

        # rank eligible nodes: mean of (binpack fit after preemption) and
        # the logistic preemption score of the evicted set
        from nomad_tpu.ops.fit import score_fit
        rows = np.flatnonzero(met)
        best_row, best_score = -1, -np.inf
        for row in rows:
            evicted = [self.cand_allocs[row][i]
                       for i in np.flatnonzero(picked[row])]
            freed = self.cand_res[row][picked[row]].sum(axis=0)
            util_after = used[row] - freed + demand
            fit = float(np.asarray(score_fit(
                cm.capacity[row:row + 1], util_after[None, :], False))[0]) / 18.0
            p_score = preemption_score(net_priority(
                [a.job.priority if a.job else 50 for a in evicted]))
            score = (fit + p_score) / 2.0
            if score > best_score:
                best_score, best_row = score, int(row)

        evicted = [self.cand_allocs[best_row][i]
                   for i in np.flatnonzero(picked[best_row])]
        evicted = self._superset_filter(
            evicted, remaining[best_row], demand)
        return best_row, evicted

    # ------------------------------------------------------------- filter

    def _superset_filter(self, picks: List, remaining: np.ndarray,
                         ask: np.ndarray) -> List:
        """Drop picks whose resources are already covered by the rest
        (reference filterSuperset: iterate largest-first, keep only while
        the remainder no longer satisfies the ask)."""
        def vec(a):
            cr = a.comparable_resources()
            return np.array([cr.cpu_shares, cr.memory_mb, cr.disk_mb], np.float32)

        picks = sorted(picks, key=lambda a: -vec(a).sum())
        kept = list(picks)
        for a in picks:
            trial = [x for x in kept if x.id != a.id]
            avail = remaining + sum((vec(x) for x in trial),
                                    np.zeros(3, np.float32))
            if np.all(avail >= ask) and trial:
                kept = trial
        return kept
