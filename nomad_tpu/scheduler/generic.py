"""Generic (service/batch) scheduler over the dense placement engine.

Reference: scheduler/generic_sched.go — Process:144, process:242,
computeJobAllocs:358, computePlacements:499-679, findPreferredNode:783,
blocked-eval creation:219-238.  The reconcile step is host-side
(nomad_tpu.scheduler.reconcile); every placement decision for an eval runs
as ONE dense kernel call (ops.place) instead of per-node iterator pulls.
"""
from __future__ import annotations

import time as _time
import uuid

from nomad_tpu.utils import generate_uuid
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.encode.matrixizer import comparable_vec

from nomad_tpu.scheduler import factory
from nomad_tpu.scheduler.placement import (
    PortClaims,
    build_allocation,
    materialize_bulk_allocs,
)
from nomad_tpu.scheduler.reconcile import AllocReconciler, PlacementRequest
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.scheduler.util import (
    adjust_queued_allocations,
    progress_made,
    tainted_nodes,
)
from nomad_tpu.structs import Allocation, Evaluation, EvalStatus, Job
from nomad_tpu.structs.alloc import AllocMetric
from nomad_tpu.structs.evaluation import EvalTrigger
from nomad_tpu.structs.plan import Plan, PlanResult

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5   # generic_sched.go:19-23
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENT_DESC = "created to place remaining allocations"
BLOCKED_EVAL_QUOTA_DESC = "created due to quota limit"


class SetStatusError(Exception):
    def __init__(self, desc: str):
        super().__init__(desc)
        self.desc = desc


class GenericScheduler:
    """One instance per eval invocation (the reference constructs a fresh
    scheduler per Process call via the factory)."""

    batch = False

    def __init__(self, state, planner):
        self.state = state            # StateSnapshot-like read view
        self.planner = planner        # Planner: submit_plan/create_evals/...
        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.deployment = None
        self.queued_allocs: Dict[str, int] = {}
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.blocked: Optional[Evaluation] = None
        self.followup_evals: List[Evaluation] = []

    # ------------------------------------------------------------- process

    def process(self, ev: Evaluation) -> None:
        self.eval = ev
        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch \
            else MAX_SERVICE_SCHEDULE_ATTEMPTS
        attempts = 0
        while attempts < limit:
            done, made_progress = self._attempt()
            if done:
                return
            qname = self.plan_result.quota_limit_reached \
                if self.plan_result is not None else ""
            if qname:
                # over-quota placements were dropped by the applier's
                # quota filter; retrying cannot help until the namespace
                # quota is raised or usage drains — block keyed on the
                # quota so the spec-upsert hook releases this eval
                blocked = self._make_blocked_eval(BLOCKED_EVAL_QUOTA_DESC)
                blocked.quota_limit_reached = qname
                self.planner.create_evals([blocked])
                self.eval.queued_allocations = dict(self.queued_allocs)
                self.eval.blocked_eval = blocked.id
                return
            # a partial commit that made progress resets the retry budget
            # (reference retryMax's reset hook + progressMade, util.go:391-425)
            attempts = 0 if made_progress else attempts + 1
            snap = self.planner.refresh_snapshot(
                self.plan_result.refresh_index if self.plan_result else 0)
            if snap is None:
                raise SetStatusError("timed out refreshing state snapshot")
            self.state = snap
        # exhausted plan attempts: roll over into a blocked eval
        if not self.batch:
            blocked = self._make_blocked_eval(BLOCKED_EVAL_MAX_PLAN_DESC,
                                              triggered_by=EvalTrigger.MAX_PLANS)
            self.planner.create_evals([blocked])
        raise SetStatusError("maximum attempts reached")

    # ------------------------------------------------------------- attempt

    def _attempt(self) -> bool:
        ev = self.eval
        self.job = self.state.job_by_id(ev.namespace, ev.job_id)
        self.failed_tg_allocs = {}
        self.followup_evals = []

        stopped = self.job is None or self.job.stopped()
        self.deployment = None
        if not stopped:
            self.deployment = self.state.latest_deployment_by_job_id(
                ev.namespace, ev.job_id)

        allocs = self.state.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(self.state, allocs)

        self.plan = ev.make_plan(self.job)
        if ev.annotate_plan:
            from nomad_tpu.structs.plan import PlanAnnotations
            self.plan.annotations = PlanAnnotations()

        reconciler = AllocReconciler(
            job=None if stopped else self.job,
            job_id=ev.job_id,
            existing=allocs,
            tainted_nodes=tainted,
            deployment=self.deployment,
            eval_id=ev.id,
            batch=self.batch,
            eval_priority=ev.priority,
        )
        results = reconciler.compute()

        # follow-up (delayed) evals must exist before allocs reference them
        for evs in results.desired_followup_evals.values():
            self.followup_evals.extend(evs)
        if self.followup_evals:
            self.planner.create_evals(self.followup_evals)

        # stops / destructive stops
        for sr in results.stop:
            self.plan.append_stopped_alloc(
                sr.alloc, sr.status_description, sr.client_status,
                sr.followup_eval_id)
        for sr in results.destructive_stop:
            self.plan.append_stopped_alloc(
                sr.alloc, sr.status_description, sr.client_status,
                sr.followup_eval_id)

        # in-place updates / attribute-only updates ride the plan as
        # same-node allocations
        for a in results.inplace_update:
            self.plan.append_alloc(a, self.job)
        for a in results.attribute_updates.values():
            self.plan.append_alloc(a, a.job)
        for a in results.disconnect_updates.values():
            self.plan.append_alloc(a, a.job)
        for a in results.reconnect_updates.values():
            self.plan.append_alloc(a, a.job)

        # deployment changes
        if results.deployment is not None:
            self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates

        if results.desired_tg_updates and self.plan.annotations is not None:
            self.plan.annotations.desired_tg_updates = results.desired_tg_updates

        # queued = placements desired this pass
        self.queued_allocs = {tg.name: 0 for tg in
                              (self.job.task_groups if self.job else [])}
        for pr in results.place:
            self.queued_allocs[pr.task_group] = \
                self.queued_allocs.get(pr.task_group, 0) + 1

        self._ext_tickets: List[int] = []
        try:
            if not stopped and results.place:
                self._compute_placements(results.place, results.stop +
                                         results.destructive_stop, allocs)

            if self.plan.is_no_op():
                self._finish_eval()
                return True, False

            # the applier releases these overlay tickets atomically with
            # the commit; the finally below is only the abandoned-plan
            # safety net (complete() is idempotent)
            tickets = list(self._ext_tickets)
            st = getattr(self, "_stack", None)
            if st is not None and getattr(st, "last_ticket", None) is not None:
                tickets.append(st.last_ticket)
            self.plan.engine_tickets = tickets

            self.plan_result = self.planner.submit_plan(self.plan)
        finally:
            # release the in-flight usage overlay: the plan is now either
            # committed into the cluster matrix or abandoned.  Exception:
            # a pipelined submit returned at evaluate time with the
            # durable commit still in flight — there the applier owns the
            # release (success: _post_commit; failure: the commit
            # thread's error path), and freeing here would show phantom
            # capacity to concurrent kernels before the write lands.
            if getattr(self.plan, "commit_inflight", False):
                if getattr(self, "_stack", None) is not None:
                    self._stack.last_ticket = None
                    self._stack = None
                self._ext_tickets = []
            else:
                if getattr(self, "_stack", None) is not None:
                    self._stack.release()
                    self._stack = None
                if self._ext_tickets:
                    from nomad_tpu.parallel.engine import get_engine
                    eng = get_engine()
                    if eng is not None:
                        for t in self._ext_tickets:
                            eng.complete(t)
                    self._ext_tickets = []
        adjust_queued_allocations(self.plan_result, self.queued_allocs)

        full, expected, actual = self.plan_result.full_commit(self.plan)
        if not full:
            return False, progress_made(self.plan_result)
        self._finish_eval()
        return True, True

    # ------------------------------------------------------------- finish

    def _finish_eval(self) -> None:
        ev = self.eval
        ev.queued_allocations = dict(self.queued_allocs)
        if self.failed_tg_allocs and self.blocked is None:
            blocked = self._make_blocked_eval(BLOCKED_EVAL_FAILED_PLACEMENT_DESC)
            blocked.status = EvalStatus.BLOCKED
            self.blocked = blocked
            self.planner.create_evals([blocked])
            ev.blocked_eval = blocked.id

    def _make_blocked_eval(self, desc: str, triggered_by: str = "") -> Evaluation:
        ev = self.eval
        classes, escaped = self._class_eligibility()
        return Evaluation(
            id=generate_uuid(),
            namespace=ev.namespace,
            priority=ev.priority,
            type=ev.type,
            triggered_by=triggered_by or EvalTrigger.QUEUED_ALLOCS,
            job_id=ev.job_id,
            status=EvalStatus.BLOCKED,
            status_description=desc,
            previous_eval=ev.id,
            class_eligibility=classes,
            escaped_computed_class=escaped,
            snapshot_index=getattr(self.state, "index", 0),
        )

    def _class_eligibility(self) -> Tuple[Dict[str, bool], bool]:
        """Which computed node classes were feasible (for unblock-on-capacity
        keying; reference EvalEligibility, context.go:252-420) — a
        vectorized groupby over the matrix's per-row class codes instead
        of the reference's per-node memoized walk."""
        classes: Dict[str, bool] = {}
        escaped = False
        if self.job is None:
            return classes, True
        for c in self.job.constraints:
            if "unique." in c.ltarget or "unique." in c.rtarget:
                escaped = True
        # device asks are per-node capacity, not class-constant: with
        # every instance taken the whole class reads infeasible, and a
        # blocked eval keyed on that verdict would never release when
        # instances free up — escape class tracking instead
        for tg in self.job.task_groups:
            for t in tg.tasks:
                if t.resources.devices:
                    escaped = True
        cm = self.state.matrix
        codes = cm.class_codes
        n_classes = len(cm.class_names)
        if n_classes == 0:
            return classes, escaped
        valid = codes >= 0
        feas_union = getattr(self, "_last_feasible_union", None)
        if feas_union is not None and feas_union.shape[0] < codes.shape[0]:
            # matrix grew since the stack compiled; unseen rows count as
            # infeasible for this eval's view
            grown = np.zeros(codes.shape[0], bool)
            grown[:feas_union.shape[0]] = feas_union
            feas_union = grown
        present = np.bincount(codes[valid], minlength=n_classes) > 0
        if feas_union is None:
            ok = present
        else:
            ok = np.bincount(codes[valid],
                             weights=feas_union[valid].astype(np.float64),
                             minlength=n_classes) > 0
        for c in np.flatnonzero(present):
            classes[cm.class_names[c]] = bool(ok[c])
        return classes, escaped

    # ------------------------------------------------------------- placing

    def _compute_placements(self, places: List[PlacementRequest],
                            stops, all_allocs: List[Allocation]) -> None:
        """Device-requesting evals serialize through the engine's gate:
        instance picks race-free across workers (basis read, placement,
        id assignment and overlay registration are atomic), mirroring how
        bulk evals serialize.  Everything else runs concurrently."""
        import contextlib

        from nomad_tpu.parallel.engine import get_engine
        eng = get_engine()
        device_eval = any(t.resources.devices
                          for tg in self.job.task_groups
                          for t in tg.tasks)
        gate = eng.bulk_gate if (eng is not None and device_eval) \
            else contextlib.nullcontext()
        with gate:
            self._compute_placements_inner(places, stops, all_allocs)
            if device_eval and eng is not None:
                contribs = []
                for node_id, allocs_ in self.plan.node_allocation.items():
                    row = self.state.matrix.row_of.get(node_id)
                    if row is None:
                        continue
                    for a_ in allocs_:
                        for tr_ in a_.allocated_resources.tasks.values():
                            for d_ in tr_.devices:
                                gid_ = (f"{d_['vendor']}/{d_['type']}/"
                                        f"{d_['name']}")
                                contribs.append(
                                    (gid_, row,
                                     len(d_.get("device_ids", []))))
                if contribs:
                    self._ext_tickets.append(eng.register_devices(
                        self.state.matrix, contribs))

    def _compute_placements_inner(self, places: List[PlacementRequest],
                                  stops, all_allocs: List[Allocation]) -> None:
        cm = self.state.matrix
        stack = DenseStack(cm, self.state.scheduler_config,
                           snapshot=self.state)
        self._stack = stack
        job = self.job
        tg_index = {tg.name: i for i, tg in enumerate(job.task_groups)}
        groups = [stack.compile_group(job, tg) for tg in job.task_groups]
        # constraint-only union, NOT g.feasible: readiness and capacity
        # are transient, and a blocked eval keyed on them would mark its
        # class ineligible forever (a down node or full device must not
        # veto the class the recovery will unblock)
        self._last_feasible_union = np.any(
            np.stack([g.class_feasible for g in groups]), axis=0)

        # proposed-usage basis: committed usage PLUS the engine's in-flight
        # overlay (placements of concurrently scheduled, not-yet-committed
        # plans) minus what this plan stops; `deltas` mirrors every
        # adjustment sparsely for the batching engine
        from nomad_tpu.parallel.engine import get_engine
        _eng = get_engine()
        used = _eng.basis_for(cm) if _eng is not None \
            and cm.used.shape[0] == cm.capacity.shape[0] else cm.used.copy()
        deltas: List[Tuple[int, np.ndarray]] = []
        freed_ports: Dict[int, Set[int]] = {}
        stopped_ids: Set[str] = set()
        for sr in stops:
            a = sr.alloc
            stopped_ids.add(a.id)
            row = cm.row_of.get(a.node_id)
            if row is None:
                continue
            cr = a.comparable_resources()
            vec = comparable_vec(cr)
            used[row] -= vec
            deltas.append((row, -vec))
            from nomad_tpu.core.plan_apply import _alloc_ports
            freed_ports.setdefault(row, set()).update(_alloc_ports(a))

        # remaining allocs for anti-affinity / spread / distinct_*
        allocs_by_tg: Dict[str, List[Allocation]] = {}
        for a in all_allocs:
            if a.id in stopped_ids or a.terminal_status():
                continue
            allocs_by_tg.setdefault(a.task_group, []).append(a)

        penalty_nodes: Dict[str, Set[str]] = {}
        for pr in places:
            if pr.is_rescheduling and pr.previous_alloc is not None:
                penalty_nodes.setdefault(pr.task_group, set()).add(
                    pr.previous_alloc.node_id)

        # sticky ephemeral disk: prefer the previous node when feasible
        # (findPreferredNode, generic_sched.go:783)
        slot_requests: List[PlacementRequest] = []
        preplaced: List[Tuple[PlacementRequest, int]] = []
        for pr in places:
            gi = tg_index[pr.task_group]
            tg = job.task_groups[gi]
            if (tg.ephemeral_disk.sticky and pr.previous_alloc is not None
                    and not pr.is_rescheduling):
                row = cm.row_of.get(pr.previous_alloc.node_id)
                if row is not None and groups[gi].feasible[row]:
                    d = groups[gi].demand
                    if np.all(used[row] + d <= cm.capacity[row]):
                        used[row] += d
                        deltas.append((row, d.astype(np.float32)))
                        preplaced.append((pr, row))
                        continue
            slot_requests.append(pr)

        # --- bulk path: groups of identical slots with no
        # placement-coupled constraints (spreads / distinct_*) place via
        # the wavefront kernel in O(waves) steps instead of an
        # O(slots) scan — the C2M-scale path (ops.place.place_bulk_jit).
        # The eval submits EVERY eligible group before waiting
        # (place_bulk_begin), so a many-small-group job (the C2M-1M
        # shape: 10 groups x count 10) is ONE chained device dispatch
        # batched with other workers' evals, not one blocking round trip
        # per group; FIFO + the engine's resolve-before-next-dispatch
        # keep group g+1 scoring against g's placements.
        BULK_MIN = 2
        by_group: Dict[int, List[PlacementRequest]] = {}
        for pr in slot_requests:
            by_group.setdefault(tg_index[pr.task_group], []).append(pr)
        bulk_results: List[Tuple[int, List[PlacementRequest], object]] = []
        scan_requests: List[PlacementRequest] = []
        from nomad_tpu.parallel.engine import get_engine
        eng = get_engine()
        pending_bulk: List[Tuple[int, List[PlacementRequest], object]] = []
        for gi, prs in by_group.items():
            g = groups[gi]
            from nomad_tpu.scheduler.stack import group_dynamic_port_count
            eligible = (len(prs) >= BULK_MIN and not g.spreads
                        and not g.distinct_hosts_job
                        and not g.distinct_hosts_tg
                        and not g.distinct_property
                        and not g.static_ports
                        and group_dynamic_port_count(g.tg) == 0
                        and not any(t.resources.devices
                                    for t in g.tg.tasks))
            if not eligible:
                scan_requests.extend(prs)
                continue
            if eng is not None:
                fut = self._place_bulk_begin(eng, cm, g, prs,
                                             allocs_by_tg, penalty_nodes,
                                             deltas, stack)
                pending_bulk.append((gi, prs, fut))
                continue
            bulk, ticket = self._place_bulk(cm, job, g, prs, allocs_by_tg,
                                            penalty_nodes, deltas, stack)
            bulk_results.append((gi, prs, bulk))
            if ticket is not None:
                self._ext_tickets.append(ticket)
        for gi, prs, fut in pending_bulk:
            assign, placed, n_eval, n_exh, scores, ticket = fut.result()
            bulk_results.append(
                (gi, prs, (assign, placed, n_eval, n_exh, scores)))
            if ticket is not None:
                self._ext_tickets.append(ticket)
        # cumulative usage for the scan path + host bookkeeping: apply
        # EVERY bulk group's placements (engine dispatch may reorder
        # parts, so no single returned matrix is complete; the engine
        # itself sees this usage through the overlay tickets)
        if bulk_results:
            from nomad_tpu import native as _native_mod
            used = used.copy()
            for gi, _prs, bulk in bulk_results:
                assign = bulk[0]
                rows_nz = np.flatnonzero(assign)
                _native_mod.scatter_add_rank1(
                    used, rows_nz, assign[rows_nz],
                    groups[gi].demand.astype(np.float32))
        slot_requests = scan_requests

        slots = [tg_index[pr.task_group] for pr in slot_requests]
        result = None
        if slots:
            inputs = stack.build_inputs(
                job, groups, slots, allocs_by_tg,
                penalty_nodes=penalty_nodes, used_override=used)
            result = stack.place(inputs, deltas)

        ports = PortClaims(cm)
        now = _time.time()
        deployment = self.plan.deployment or self.deployment

        def metric_for(i: Optional[int]) -> AllocMetric:
            m = AllocMetric()
            if result is not None and i is not None:
                m.nodes_evaluated = int(result.nodes_evaluated[i])
                m.nodes_exhausted = int(result.nodes_exhausted[i])
                entries = []
                for k in range(result.top_nodes.shape[1]):
                    r = int(result.top_nodes[i, k])
                    s = float(result.top_scores[i, k])
                    if r >= 0 and s > -np.inf and cm.node_ids[r]:
                        entries.append({"node_id": cm.node_ids[r],
                                        "norm_score": round(s, 6)})
                m.populate_score_meta(entries)
            m.allocation_time_s = 0.0
            return m

        def assign_devices(pr, tg, node, row, preempted) -> Optional[Dict]:
            """Assign device instances for every device request of the
            group (scheduler/device.go AllocateDevice), attempting device
            preemption (PreemptForDevice) when instances are exhausted.
            Returns {task: [assignment dicts]} or None on failure; appends
            extra evictions to `preempted` in place."""
            wants = [(t, req) for t in tg.tasks for req in t.resources.devices]
            if not wants:
                return {}
            from nomad_tpu.scheduler.devices import assign_device_instances
            # instance ids are picked against the LIVE store view: under
            # the device gate all prior device plans have committed, so
            # the freshest state (not this eval's older snapshot) is what
            # prevents id collisions at the applier
            live_view = getattr(self.state, "_store", None) or self.state
            node_allocs = [a for a in live_view.allocs_by_node(node.id)
                           if not a.terminal_status()]
            node_allocs += self.plan.node_allocation.get(node.id, [])
            # allocs this plan already stops or preempts no longer hold
            # their device instances
            evicted_ids = {a.id for a in preempted}
            evicted_ids |= stopped_ids
            evicted_ids |= {a.id for a in
                            self.plan.node_preemptions.get(node.id, [])}
            out: Dict[str, List[dict]] = {}
            granted: Dict[str, set] = {}   # in-flight grants of THIS alloc
            for t, req in wants:
                live = [a for a in node_allocs if a.id not in evicted_ids]
                got = assign_device_instances(node, live, req,
                                              extra_used=granted)
                if got is None and preemption_on:
                    nonlocal preemptor
                    if preemptor is None:
                        from nomad_tpu.scheduler.preemption import Preemptor
                        preemptor = Preemptor(self.state, job.priority,
                                              seed=self.eval.id)
                    extra = preemptor.preempt_for_device(
                        node, live, req, exclude=evicted_ids)
                    if extra:
                        preempted.extend(extra)
                        evicted_ids.update(a.id for a in extra)
                        live = [a for a in node_allocs
                                if a.id not in evicted_ids]
                        got = assign_device_instances(node, live, req,
                                                      extra_used=granted)
                if got is None:
                    return None
                gid = f"{got['vendor']}/{got['type']}/{got['name']}"
                granted.setdefault(gid, set()).update(got["device_ids"])
                out.setdefault(t.name, []).append(got)
            return out

        def place_on(pr: PlacementRequest, row: int, metric: AllocMetric,
                     preempted=None, extra_freed=None,
                     alt_rows=None) -> bool:
            gi = tg_index[pr.task_group]
            tg = job.task_groups[gi]
            node_id = cm.node_ids[row]
            node = self.state.node_by_id(node_id)
            dep_id = ""
            if deployment is not None and tg.name in deployment.task_groups:
                dep_id = deployment.id
            # no copy: device-preemption evictions appended by
            # assign_devices must stay visible to the caller for
            # usage/invalidate bookkeeping
            preempted = preempted if preempted is not None else []
            devices = assign_devices(pr, tg, node, row, preempted) \
                if node is not None else {}
            if devices is None:
                # the dense kernel scores cpu/mem, not per-node device
                # instances; earlier placements of THIS eval may have
                # claimed the node's instances — fall back to the next
                # best candidates from the kernel's top-K (the reference
                # iterator simply pulls the next node, rank.go:193)
                alt_list = [] if alt_rows is None else list(alt_rows)
                for alt in alt_list:
                    alt = int(alt)
                    if alt < 0 or alt == row or not cm.node_ids[alt]:
                        continue
                    if not groups[gi].feasible[alt]:
                        continue
                    d = groups[gi].demand
                    if not np.all(used[alt] + d <= cm.capacity[alt]):
                        continue
                    alt_node = self.state.node_by_id(cm.node_ids[alt])
                    devices = assign_devices(pr, tg, alt_node, alt,
                                             preempted) \
                        if alt_node is not None else {}
                    if devices is not None:
                        row, node_id, node = alt, cm.node_ids[alt], alt_node
                        used[row] += d
                        break
                else:
                    self._fail_placement(pr, metric, "devices exhausted")
                    return False
            freed = set(freed_ports.get(row, set()))
            if extra_freed:
                freed |= extra_freed
            alloc = build_allocation(
                job=job, tg=tg, name=pr.name, node_id=node_id,
                node_name=node.name if node else "", eval_id=self.eval.id,
                row=row, ports=ports, freed_ports=freed,
                metric=metric, previous=pr.previous_alloc,
                deployment_id=dep_id, is_canary=pr.is_canary,
                is_rescheduling=pr.is_rescheduling, now=now,
                task_devices=devices)
            if alloc is None:
                self._fail_placement(pr, metric, "ports exhausted")
                return False
            if pr.previous_alloc is not None:
                pr.previous_alloc.next_allocation = alloc.id
            if preempted:
                # handlePreemptions (generic_sched.go:822-843)
                alloc.preempted_allocations = [a.id for a in preempted]
                for a in preempted:
                    self.plan.append_preempted_alloc(a, alloc.id)
            self.plan.append_alloc(alloc, None)
            if pr.is_canary and self.plan.deployment is not None:
                state = self.plan.deployment.task_groups.get(tg.name)
                if state is not None:
                    state.placed_canaries.append(alloc.id)
            return True

        # preemption for failed slots (BinPackIterator's evict path,
        # rank.go:500-530; gated by SchedulerConfiguration like the
        # reference's per-scheduler-type preemption config)
        preemptor = None
        scheduler_type = "batch" if self.batch else "service"
        preemption_on = self.state.scheduler_config.preemption_enabled(
            scheduler_type)

        preempt_cache: Dict[int, List] = {}

        def try_preempt(pr: PlacementRequest, i: Optional[int]) -> bool:
            nonlocal preemptor
            if not preemption_on:
                return False
            if preemptor is None:
                from nomad_tpu.scheduler.preemption import Preemptor
                preemptor = Preemptor(self.state, job.priority,
                                      seed=self.eval.id)
            gi = tg_index[pr.task_group]
            cache = preempt_cache.setdefault(gi, [])
            if not cache:
                # one kernel round serves a batch of failed slots (each
                # find round trip costs ~a tunnel RTT)
                cache.extend(preemptor.find_many(
                    groups[gi].feasible, groups[gi].demand, used, 64,
                    static_ports=groups[gi].static_ports,
                    feasible_pre_ports=groups[gi].feasible_pre_ports,
                    device_blocked=groups[gi].device_blocked))
            if not cache:
                return False
            row, evicted = cache.pop(0)
            # ports held by the evicted allocs become claimable — but only
            # commit that (and the usage adjustments) if the placement
            # actually lands, else later placements would claim ports of
            # allocs that keep running
            from nomad_tpu.core.plan_apply import _alloc_ports
            evicted_ports = set()
            for a in evicted:
                evicted_ports.update(_alloc_ports(a))
            metric = metric_for(i)
            if not place_on(pr, row, metric, preempted=evicted,
                            extra_freed=evicted_ports):
                return True   # failure already recorded by place_on
            # `evicted` may have grown inside place_on (device
            # preemption); account for everything it now holds
            for a in evicted:
                evicted_ports.update(_alloc_ports(a))
                cr = a.comparable_resources()
                used[row] -= comparable_vec(cr)
            freed_ports.setdefault(row, set()).update(evicted_ports)
            used[row] += groups[gi].demand
            preemptor.invalidate({a.id for a in evicted})
            return True

        def account_device_evictions(row, extra) -> None:
            """Device-preemption evictions made inside place_on on a
            non-preemption path still free usage and must not be chosen
            again by later slots."""
            if not extra:
                return
            for a in extra:
                used[row] -= comparable_vec(a.comparable_resources())
                freed_ports.setdefault(row, set()).update(_alloc_ports_fn(a))
            if preemptor is not None:
                preemptor.invalidate({a.id for a in extra})

        from nomad_tpu.core.plan_apply import _alloc_ports as _alloc_ports_fn

        for pr, row in preplaced:
            extra = []
            place_on(pr, row, metric_for(None), preempted=extra)
            account_device_evictions(row, extra)

        # bulk-kernel placements: one native expand_pairs call flattens
        # each group's (row, count, score) triples to per-alloc arrays,
        # and plain new placements materialize through the batch
        # constructor instead of K build_allocation round trips
        for gi, prs, bulk in bulk_results:
            assign, placed, n_eval, n_exh, bscores = bulk
            from nomad_tpu import native as _native_mod
            rows_nz = np.flatnonzero(assign)
            flat_rows, flat_scores = _native_mod.expand_pairs(
                rows_nz, assign[rows_nz], np.asarray(bscores)[rows_nz])
            n_placed = min(len(flat_rows), len(prs))
            tg = job.task_groups[gi]
            fast = (n_placed > 0
                    and not tg.networks
                    and not any(t.resources.networks for t in tg.tasks)
                    and all(pr.previous_alloc is None
                            and not pr.is_canary
                            and not pr.is_rescheduling
                            for pr in prs[:n_placed]))
            if fast:
                dep_id = ""
                if deployment is not None \
                        and tg.name in deployment.task_groups:
                    dep_id = deployment.id
                node_names = {}
                for row in rows_nz:
                    row = int(row)
                    node = self.state.node_by_id(cm.node_ids[row])
                    node_names[row] = node.name if node else ""
                for alloc in materialize_bulk_allocs(
                        job, tg, [pr.name for pr in prs[:n_placed]],
                        flat_rows[:n_placed], flat_scores[:n_placed],
                        cm.node_ids, node_names, self.eval.id, dep_id,
                        int(n_eval), int(n_exh), now):
                    self.plan.append_alloc(alloc, None)
            else:
                for pr, row, sc in zip(prs, flat_rows, flat_scores):
                    row = int(row)
                    m = AllocMetric()
                    m.nodes_evaluated = n_eval
                    m.nodes_exhausted = n_exh
                    if cm.node_ids[row]:
                        m.populate_score_meta([{
                            "node_id": cm.node_ids[row],
                            "norm_score": round(float(sc), 6)}])
                    place_on(pr, row, m)
            for pr in prs[n_placed:]:
                m = AllocMetric()
                m.nodes_evaluated = n_eval
                m.nodes_exhausted = n_exh
                if not try_preempt(pr, None):
                    self._fail_placement(pr, m, "exhausted")
        if result is not None:
            for i, pr in enumerate(slot_requests):
                row = int(result.node[i])
                if row < 0:
                    if not try_preempt(pr, i):
                        self._fail_placement(pr, metric_for(i), "exhausted")
                else:
                    extra = []
                    alts = result.top_nodes[i] if result is not None else []
                    place_on(pr, row, metric_for(i), preempted=extra,
                             alt_rows=alts)
                    account_device_evictions(row, extra)

    @staticmethod
    def _bulk_node_fields(cm, g, allocs_by_tg, penalty_nodes):
        """(penalty bool[N], coll0 i32[N]) for one bulk group."""
        N = cm.n_rows
        penalty = np.zeros(N, bool)
        for nid in (penalty_nodes or {}).get(g.tg.name, ()):
            row = cm.row_of.get(nid)
            if row is not None:
                penalty[row] = True
        coll0 = np.zeros(N, np.int32)
        for a in allocs_by_tg.get(g.tg.name, []):
            row = cm.row_of.get(a.node_id)
            if row is not None:
                coll0[row] += 1
        return penalty, coll0

    def _place_bulk_begin(self, eng, cm, g, prs, allocs_by_tg,
                          penalty_nodes, deltas, stack):
        """Enqueue one group's wavefront placement; returns the engine
        Future (see engine.place_bulk_begin for ordering semantics)."""
        penalty, coll0 = self._bulk_node_fields(cm, g, allocs_by_tg,
                                                penalty_nodes)
        return eng.place_bulk_begin(
            cm, feasible=g.feasible,
            affinity=g.affinity.astype(np.float32),
            has_affinity=bool(g.has_affinity),
            desired=max(g.tg.count, 1), penalty=penalty,
            coll0=coll0, demand=g.demand.astype(np.float32),
            count=len(prs), deltas=deltas,
            spread_algorithm=stack.spread_algorithm,
            # namespace = wave-lane key: evals from different namespaces
            # are independent waves and may score concurrently on the
            # 2-D mesh's wave columns
            wave_key=self.job.namespace)

    def _place_bulk(self, cm, job, g, prs, allocs_by_tg, penalty_nodes,
                    deltas, stack):
        """Wavefront placement of len(prs) identical slots of group `g`.
        With the engine present this coalesces with concurrent bulk evals
        into ONE chained device dispatch (engine.place_bulk ->
        ops.place.place_bulk_batch_jit) — conflict-free by chaining, no
        serializing gate needed.  Returns ((assign i32[N], placed,
        nodes_evaluated, nodes_exhausted, scores f32[N],
        used_after f32[N, R]), overlay ticket or None)."""
        import jax

        from nomad_tpu.ops.place import place_bulk_jit, unpack_bulk
        from nomad_tpu.parallel.engine import get_engine

        eng = get_engine()
        N = cm.n_rows
        penalty, coll0 = self._bulk_node_fields(cm, g, allocs_by_tg,
                                                penalty_nodes)

        if eng is not None:
            assign, placed, n_eval, n_exh, scores, ticket = \
                eng.place_bulk(
                    cm, feasible=g.feasible,
                    affinity=g.affinity.astype(np.float32),
                    has_affinity=bool(g.has_affinity),
                    desired=max(g.tg.count, 1), penalty=penalty,
                    coll0=coll0, demand=g.demand.astype(np.float32),
                    count=len(prs), deltas=deltas,
                    spread_algorithm=stack.spread_algorithm,
                    wave_key=job.namespace)
            return ((assign, placed, n_eval, n_exh, scores), ticket)

        base = cm.used.copy()
        for row, vec in deltas:       # this eval's stops/preplacements
            if row < N:
                base[row] += vec
        packed = place_bulk_jit(
            np.ascontiguousarray(cm.capacity),
            np.ascontiguousarray(base.astype(np.float32)),
            g.feasible, g.affinity.astype(np.float32),
            bool(g.has_affinity), np.int32(max(g.tg.count, 1)), penalty,
            coll0, g.demand.astype(np.float32), np.int32(len(prs)),
            spread_algorithm=stack.spread_algorithm)
        assign, placed, n_eval, n_exh, scores, _waves, _used_f = \
            unpack_bulk(jax.device_get(packed))
        return ((assign, int(placed), int(n_eval), int(n_exh),
                 np.asarray(scores)), None)

    def _fail_placement(self, pr: PlacementRequest, metric: AllocMetric,
                        reason: str) -> None:
        prev = self.failed_tg_allocs.get(pr.task_group)
        if prev is not None:
            prev.coalesced_failures += 1
        else:
            metric.dimension_exhausted[reason] = 1
            self.failed_tg_allocs[pr.task_group] = metric
        self.eval.queued_allocations = self.queued_allocs


class ServiceScheduler(GenericScheduler):
    batch = False


class BatchScheduler(GenericScheduler):
    batch = True
