"""Scheduler test harness (reference: scheduler/testing.go:45-302).

A real StateStore + a fake Planner that records submitted plans and created
evals, and self-applies plans through the real PlanApplier (the reference
harness applies via UpsertPlanResults).  `reject_plan` forces the
state-refresh / partial-commit path like the reference's RejectPlan hook.
"""
from __future__ import annotations

import itertools
from typing import List, Optional

from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.scheduler import factory
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Evaluation
from nomad_tpu.structs.plan import Plan, PlanResult

factory._register_builtins()


class Harness:
    def __init__(self, store: Optional[StateStore] = None):
        self.store = store or StateStore()
        self.applier = PlanApplier(self.store)
        self.applier.on_preempted = self._preemption_evals
        self.plans: List[Plan] = []
        self.results: List[PlanResult] = []
        self.create_evals_list: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self.eval_updates: List[Evaluation] = []
        self.reject_plan = False
        self._index = itertools.count(1000)

    # ------------------------------------------------------------- planner

    def submit_plan(self, plan: Plan) -> PlanResult:
        self.plans.append(plan)
        if self.reject_plan:
            result = PlanResult()
            result.refresh_index = self.store.latest_index
            self.results.append(result)
            return result
        result = self.applier.apply(plan)
        self.results.append(result)
        return result

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.create_evals_list.extend(evals)
        self.store.upsert_evals(self.next_index(), [e.copy() for e in evals])

    def update_eval(self, ev: Evaluation) -> None:
        self.eval_updates.append(ev)

    def reblock_eval(self, ev: Evaluation) -> None:
        self.reblock_evals.append(ev)

    def refresh_snapshot(self, min_index: int = 0):
        return self.store.snapshot()

    # ------------------------------------------------------------- helpers

    def _preemption_evals(self, preempted) -> None:
        seen = set()
        for a in preempted:
            key = (a.namespace, a.job_id)
            if key in seen:
                continue
            seen.add(key)
            from nomad_tpu.structs import Evaluation
            self.create_evals([Evaluation(
                namespace=a.namespace, job_id=a.job_id,
                type=a.job.type if a.job else "service",
                triggered_by="preemption", status="pending")])

    def next_index(self) -> int:
        return next(self._index)

    def process(self, scheduler_type: str, ev: Evaluation) -> None:
        snap = self.store.snapshot()
        sched = factory.new_scheduler(scheduler_type, snap, self)
        sched.process(ev)
        self.last_scheduler = sched
