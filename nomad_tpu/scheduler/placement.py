"""Placement materialization: PlaceResult rows -> Allocation objects.

Port-offer construction stays on the host (SURVEY.md section 7 'hard
parts': dynamic port assignment is inherently sequential; the device checks
capacity/collisions, the host constructs the concrete offer — mirroring the
reference split where the plan applier re-validates).
"""
from __future__ import annotations

import uuid

from nomad_tpu.utils import generate_uuid
from typing import Dict, List, Optional, Set

import numpy as np

from nomad_tpu.encode.matrixizer import ClusterMatrix
from nomad_tpu.structs import Allocation, AllocClientStatus, AllocDesiredStatus, Job, TaskGroup
from nomad_tpu.structs.alloc import (
    AllocatedResources,
    AllocatedTaskResources,
    AllocMetric,
    RescheduleEvent,
    RescheduleTracker,
)
from nomad_tpu.structs.resources import NetworkPort, NetworkResource


class PortClaims:
    """In-plan port claims per node row (plan-local view on top of the
    committed bitsets)."""

    def __init__(self, cm: ClusterMatrix):
        self.cm = cm
        self.claimed: Dict[int, Set[int]] = {}

    def _is_free(self, row: int, port: int, freed: Set[int]) -> bool:
        if port in self.claimed.get(row, ()):
            return False
        if port in freed:
            return True
        bit = (self.cm.port_words[row, port >> 5] >> np.uint32(port & 31)) & 1
        return not bit

    def claim_static(self, row: int, port: int, freed: Set[int]) -> bool:
        if not self._is_free(row, port, freed):
            return False
        self.claimed.setdefault(row, set()).add(port)
        return True

    def assign_dynamic(self, row: int, freed: Set[int]) -> Optional[int]:
        """First free port in the node's dynamic range, via a vectorized
        scan of the port bitset words (the naive per-port loop was O(range)
        per assignment in the placement hot path)."""
        lo = int(self.cm.dyn_port_lo[row])
        hi = int(self.cm.dyn_port_hi[row])
        w0, w1 = lo >> 5, (hi >> 5) + 1
        words = self.cm.port_words[row, w0:w1].copy()
        # freed ports clear first, plan-local claims override after — a
        # port both freed (by a stop/eviction) and already claimed by this
        # plan must stay used (mirrors _is_free's claimed-first ordering)
        for p in freed:
            if lo <= p <= hi:
                words[(p >> 5) - w0] &= ~np.uint32(1 << (p & 31))
        for p in self.claimed.get(row, ()):
            if lo <= p <= hi:
                words[(p >> 5) - w0] |= np.uint32(1 << (p & 31))
        # mask bits outside [lo, hi] as used
        words[0] |= ~(np.uint32(0xFFFFFFFF) << np.uint32(lo & 31))
        hi_bit = hi & 31
        last_mask = np.uint32(
            (np.uint64(1) << np.uint64(hi_bit + 1)) - np.uint64(1))
        words[-1] |= ~last_mask
        free = np.flatnonzero(words != np.uint32(0xFFFFFFFF))
        if len(free) == 0:
            return None
        w = int(free[0])
        inv = int(~words[w] & np.uint32(0xFFFFFFFF))
        bit = (inv & -inv).bit_length() - 1   # lowest free bit
        p = ((w0 + w) << 5) + bit
        self.claimed.setdefault(row, set()).add(p)
        return p


def build_allocation(
    job: Job,
    tg: TaskGroup,
    name: str,
    node_id: str,
    node_name: str,
    eval_id: str,
    row: int,
    ports: PortClaims,
    freed_ports: Set[int],
    metric: AllocMetric,
    previous: Optional[Allocation] = None,
    deployment_id: str = "",
    is_canary: bool = False,
    is_rescheduling: bool = False,
    now: float = 0.0,
    task_devices: Optional[Dict[str, List[dict]]] = None,
) -> Optional[Allocation]:
    """Construct the Allocation for one selected placement; returns None if
    port assignment fails (caller treats as exhausted node).
    `task_devices` carries pre-assigned device instances per task name
    (scheduler/device.go AllocateDevice output)."""
    tasks: Dict[str, AllocatedTaskResources] = {}
    for t in tg.tasks:
        nets = []
        for net in t.resources.networks:
            nets.append(_materialize_net(net, row, ports, freed_ports))
            if nets[-1] is None:
                return None
        tasks[t.name] = AllocatedTaskResources(
            cpu_shares=t.resources.cpu,
            memory_mb=t.resources.memory_mb,
            memory_max_mb=t.resources.memory_max_mb,
            networks=[n for n in nets if n is not None],
            devices=list((task_devices or {}).get(t.name, ())),
        )
    shared_nets = []
    shared_ports: List[NetworkPort] = []
    for net in tg.networks:
        m = _materialize_net(net, row, ports, freed_ports)
        if m is None:
            return None
        shared_nets.append(m)
        shared_ports.extend(m.reserved_ports + m.dynamic_ports)

    alloc = Allocation(
        id=generate_uuid(),
        namespace=job.namespace,
        eval_id=eval_id,
        name=name,
        node_id=node_id,
        node_name=node_name,
        job_id=job.id,
        job=job,
        task_group=tg.name,
        allocated_resources=AllocatedResources(
            tasks=tasks,
            shared_disk_mb=tg.ephemeral_disk.size_mb,
            shared_networks=shared_nets,
            shared_ports=shared_ports,
        ),
        desired_status=AllocDesiredStatus.RUN,
        client_status=AllocClientStatus.PENDING,
        metrics=metric,
        deployment_id=deployment_id,
        create_time=now,
        modify_time=now,
    )
    if is_canary:
        alloc.deployment_status = {"canary": True, "healthy": None}
    if previous is not None:
        alloc.previous_allocation = previous.id
        if is_rescheduling:
            events = list(previous.reschedule_tracker.events) \
                if previous.reschedule_tracker else []
            events.append(RescheduleEvent(
                reschedule_time=now, prev_alloc_id=previous.id,
                prev_node_id=previous.node_id))
            alloc.reschedule_tracker = RescheduleTracker(events=events)
    return alloc


def materialize_bulk_allocs(
    job: Job,
    tg: TaskGroup,
    names: List[str],
    rows: np.ndarray,
    scores: np.ndarray,
    node_ids: List[str],
    node_names: Dict[int, str],
    eval_id: str,
    deployment_id: str,
    n_eval: int,
    n_exh: int,
    now: float,
) -> List[Allocation]:
    """Batch materialization for the bulk wavefront path: the resolved
    sparse output (already expanded to per-alloc `rows`/`scores` by
    native.expand_pairs) becomes Allocation records in one pass.

    Bulk-eligible groups have no ports, devices, or networks, so every
    alloc's resources are identical — ONE immutable AllocatedResources
    template is shared across the batch (read-only everywhere downstream,
    and it makes comparable_resources() memoization hit group-wide).
    Per-row AllocMetric instances are likewise shared by allocs landing
    on the same node.  uuids come from one native format_uuids call
    instead of K generate_uuid round trips."""
    from nomad_tpu import native as _native

    k_total = len(names)
    ids = _native.format_uuids(k_total)
    tasks = {
        t.name: AllocatedTaskResources(
            cpu_shares=t.resources.cpu,
            memory_mb=t.resources.memory_mb,
            memory_max_mb=t.resources.memory_max_mb,
            networks=[], devices=[])
        for t in tg.tasks}
    shared_res = AllocatedResources(
        tasks=tasks, shared_disk_mb=tg.ephemeral_disk.size_mb,
        shared_networks=[], shared_ports=[])
    metric_by_row: Dict[int, AllocMetric] = {}
    out: List[Allocation] = []
    for k in range(k_total):
        row = int(rows[k])
        m = metric_by_row.get(row)
        if m is None:
            m = AllocMetric()
            m.nodes_evaluated = n_eval
            m.nodes_exhausted = n_exh
            nid = node_ids[row]
            if nid:
                m.populate_score_meta([{
                    "node_id": nid,
                    "norm_score": round(float(scores[k]), 6)}])
            m.allocation_time_s = 0.0
            metric_by_row[row] = m
        out.append(Allocation(
            id=ids[k],
            namespace=job.namespace,
            eval_id=eval_id,
            name=names[k],
            node_id=node_ids[row],
            node_name=node_names.get(row, ""),
            job_id=job.id,
            job=job,
            task_group=tg.name,
            allocated_resources=shared_res,
            desired_status=AllocDesiredStatus.RUN,
            client_status=AllocClientStatus.PENDING,
            metrics=m,
            deployment_id=deployment_id,
            create_time=now,
            modify_time=now))
    return out


def _materialize_net(net: NetworkResource, row: int, ports: PortClaims,
                     freed: Set[int]) -> Optional[NetworkResource]:
    out = net.copy()
    for p in out.reserved_ports:
        if not ports.claim_static(row, p.value, freed):
            return None
    for p in out.dynamic_ports:
        got = ports.assign_dynamic(row, freed)
        if got is None:
            return None
        p.value = got
    return out
