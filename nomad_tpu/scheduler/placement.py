"""Placement materialization: PlaceResult rows -> Allocation objects.

Port-offer construction stays on the host (SURVEY.md section 7 'hard
parts': dynamic port assignment is inherently sequential; the device checks
capacity/collisions, the host constructs the concrete offer — mirroring the
reference split where the plan applier re-validates).
"""
from __future__ import annotations

import uuid

from nomad_tpu.utils import generate_uuid
from typing import Dict, List, Optional, Set

import numpy as np

from nomad_tpu.encode.matrixizer import ClusterMatrix
from nomad_tpu.structs import Allocation, AllocClientStatus, AllocDesiredStatus, Job, TaskGroup
from nomad_tpu.structs.alloc import (
    AllocatedResources,
    AllocatedTaskResources,
    AllocMetric,
    RescheduleEvent,
    RescheduleTracker,
)
from nomad_tpu.structs.resources import NetworkPort, NetworkResource


class PortClaims:
    """In-plan port claims per node row (plan-local view on top of the
    committed bitsets)."""

    def __init__(self, cm: ClusterMatrix):
        self.cm = cm
        self.claimed: Dict[int, Set[int]] = {}

    def _is_free(self, row: int, port: int, freed: Set[int]) -> bool:
        if port in self.claimed.get(row, ()):
            return False
        if port in freed:
            return True
        bit = (self.cm.port_words[row, port >> 5] >> np.uint32(port & 31)) & 1
        return not bit

    def claim_static(self, row: int, port: int, freed: Set[int]) -> bool:
        if not self._is_free(row, port, freed):
            return False
        self.claimed.setdefault(row, set()).add(port)
        return True

    def assign_dynamic(self, row: int, freed: Set[int]) -> Optional[int]:
        lo = int(self.cm.dyn_port_lo[row])
        hi = int(self.cm.dyn_port_hi[row])
        for p in range(lo, hi + 1):
            if self._is_free(row, p, freed):
                self.claimed.setdefault(row, set()).add(p)
                return p
        return None


def build_allocation(
    job: Job,
    tg: TaskGroup,
    name: str,
    node_id: str,
    node_name: str,
    eval_id: str,
    row: int,
    ports: PortClaims,
    freed_ports: Set[int],
    metric: AllocMetric,
    previous: Optional[Allocation] = None,
    deployment_id: str = "",
    is_canary: bool = False,
    is_rescheduling: bool = False,
    now: float = 0.0,
) -> Optional[Allocation]:
    """Construct the Allocation for one selected placement; returns None if
    port assignment fails (caller treats as exhausted node)."""
    tasks: Dict[str, AllocatedTaskResources] = {}
    for t in tg.tasks:
        nets = []
        for net in t.resources.networks:
            nets.append(_materialize_net(net, row, ports, freed_ports))
            if nets[-1] is None:
                return None
        tasks[t.name] = AllocatedTaskResources(
            cpu_shares=t.resources.cpu,
            memory_mb=t.resources.memory_mb,
            memory_max_mb=t.resources.memory_max_mb,
            networks=[n for n in nets if n is not None],
        )
    shared_nets = []
    shared_ports: List[NetworkPort] = []
    for net in tg.networks:
        m = _materialize_net(net, row, ports, freed_ports)
        if m is None:
            return None
        shared_nets.append(m)
        shared_ports.extend(m.reserved_ports + m.dynamic_ports)

    alloc = Allocation(
        id=generate_uuid(),
        namespace=job.namespace,
        eval_id=eval_id,
        name=name,
        node_id=node_id,
        node_name=node_name,
        job_id=job.id,
        job=job,
        task_group=tg.name,
        allocated_resources=AllocatedResources(
            tasks=tasks,
            shared_disk_mb=tg.ephemeral_disk.size_mb,
            shared_networks=shared_nets,
            shared_ports=shared_ports,
        ),
        desired_status=AllocDesiredStatus.RUN,
        client_status=AllocClientStatus.PENDING,
        metrics=metric,
        deployment_id=deployment_id,
        create_time=now,
        modify_time=now,
    )
    if is_canary:
        alloc.deployment_status = {"canary": True, "healthy": None}
    if previous is not None:
        alloc.previous_allocation = previous.id
        if is_rescheduling:
            events = list(previous.reschedule_tracker.events) \
                if previous.reschedule_tracker else []
            events.append(RescheduleEvent(
                reschedule_time=now, prev_alloc_id=previous.id,
                prev_node_id=previous.node_id))
            alloc.reschedule_tracker = RescheduleTracker(events=events)
    return alloc


def _materialize_net(net: NetworkResource, row: int, ports: PortClaims,
                     freed: Set[int]) -> Optional[NetworkResource]:
    out = net.copy()
    for p in out.reserved_ports:
        if not ports.claim_static(row, p.value, freed):
            return None
    for p in out.dynamic_ports:
        got = ports.assign_dynamic(row, freed)
        if got is None:
            return None
        p.value = got
    return out
