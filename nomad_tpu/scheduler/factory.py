"""Scheduler factory registry (reference: scheduler/scheduler.go:24-46).

Same plugin boundary: the server's workers look schedulers up by eval type.
The TPU-native engines register under the reference's names (service,
batch, system, sysbatch) — there is no separate "-tpu" suffix because here
the dense engine *is* the scheduler, not a sidecar.
"""
from __future__ import annotations

from typing import Callable, Dict

SCHEDULER_VERSION = 1

_registry: Dict[str, Callable] = {}


def register(name: str, factory: Callable) -> None:
    _registry[name] = factory


def new_scheduler(name: str, state, planner):
    """Reference NewScheduler (scheduler.go:33-40)."""
    if not _registry:
        _register_builtins()
    try:
        factory = _registry[name]
    except KeyError:
        raise ValueError(f"unknown scheduler '{name}'") from None
    return factory(state, planner)


def builtin_schedulers() -> Dict[str, Callable]:
    return dict(_registry)


def _register_builtins() -> None:
    from nomad_tpu.scheduler.generic import BatchScheduler, ServiceScheduler
    from nomad_tpu.scheduler.system import SysBatchScheduler, SystemScheduler
    register("service", ServiceScheduler)
    register("batch", BatchScheduler)
    register("system", SystemScheduler)
    register("sysbatch", SysBatchScheduler)
