"""Scheduler layer: dense TPU scheduling engine + host-side reconciler.

Reference: scheduler/ in hollowsunsets/nomad.  The lazy pull-based
RankIterator pipeline is replaced by batched dense kernels in
`nomad_tpu.ops`; this package holds the schedulers that drive them, the
reconciler, the factory registry, and the test harness.
"""
