"""Version constraint matching (reference: hashicorp/go-version as used by
scheduler/feasible.go checkVersionMatch; semver mode rejects pre-release
versions unless explicitly constrained, like ConstraintSemver).
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z.-]+))?(?:\+[0-9A-Za-z.-]+)?$")


class Version:
    __slots__ = ("segments", "prerelease", "raw")

    def __init__(self, raw: str):
        m = _VERSION_RE.match(raw.strip())
        if not m:
            raise ValueError(f"invalid version {raw!r}")
        self.raw = raw
        segs = [int(x) for x in m.group(1).split(".")]
        while len(segs) < 3:
            segs.append(0)
        self.segments = tuple(segs)
        self.prerelease = m.group(2) or ""

    def _pre_key(self) -> Tuple:
        # a version with a prerelease sorts before the same release
        if not self.prerelease:
            return (1,)
        parts = []
        for p in self.prerelease.split("."):
            parts.append((0, int(p)) if p.isdigit() else (1, p))
        return (0, tuple(parts))

    def key(self) -> Tuple:
        return (self.segments, self._pre_key())

    def __lt__(self, other): return self.key() < other.key()
    def __le__(self, other): return self.key() <= other.key()
    def __gt__(self, other): return self.key() > other.key()
    def __ge__(self, other): return self.key() >= other.key()
    def __eq__(self, other): return self.key() == other.key()
    def __hash__(self): return hash(self.key())


_CONSTRAINT_RE = re.compile(r"^\s*(>=|<=|!=|~>|>|<|=)?\s*(.+?)\s*$")


def parse_constraints(spec: str) -> List[Tuple[str, Version]]:
    out = []
    for part in spec.split(","):
        m = _CONSTRAINT_RE.match(part)
        if not m or not m.group(2):
            raise ValueError(f"invalid constraint {part!r}")
        out.append((m.group(1) or "=", Version(m.group(2))))
    return out


def _check_one(op: str, v: Version, target: Version) -> bool:
    if op == "=":
        return v == target
    if op == "!=":
        return v != target
    if op == ">":
        return v > target
    if op == "<":
        return v < target
    if op == ">=":
        return v >= target
    if op == "<=":
        return v <= target
    if op == "~>":
        # pessimistic: >= target, and the segment one finer than specified
        # must not roll over (go-version Constraint semantics)
        if v < target:
            return False
        spec_len = len(target.raw.lstrip("v").split("-")[0].split("."))
        lock = max(spec_len - 1, 1)
        return v.segments[:lock] == target.segments[:lock]
    return False


def version_matches(value: str, spec: str, semver: bool = False) -> bool:
    """True iff `value` satisfies the comma-separated constraint `spec`.
    semver mode: pre-release values only match when every constraint
    operand also carries a pre-release (hashicorp/go-version
    WithoutPrerelease semantics used by ConstraintSemver)."""
    try:
        v = Version(value)
        cons = parse_constraints(spec)
    except ValueError:
        return False
    if semver and v.prerelease and not all(t.prerelease for _, t in cons):
        return False
    return all(_check_one(op, v, target) for op, target in cons)
