"""Scheduler utilities (reference: scheduler/util.go — taintedNodes:427,
readyNodesInDCs:351, progressMade:417, adjustQueuedAllocations:1049).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

from nomad_tpu.structs import Allocation, Evaluation, Node
from nomad_tpu.structs.node import NodeStatus
from nomad_tpu.structs.plan import PlanResult


def tainted_nodes(snapshot, allocs: Iterable[Allocation]) -> Dict[str, Optional[Node]]:
    """Nodes referenced by allocs that are down / draining / disconnected
    (or gone).  Missing nodes map to None (treated as down)."""
    out: Dict[str, Optional[Node]] = {}
    seen: Set[str] = set()
    for a in allocs:
        if a.node_id in seen:
            continue
        seen.add(a.node_id)
        node = snapshot.node_by_id(a.node_id)
        if node is None:
            out[a.node_id] = None
        elif node.terminal_status() or node.draining or \
                node.status == NodeStatus.DISCONNECTED:
            out[a.node_id] = node
    return out


def progress_made(result: Optional[PlanResult]) -> bool:
    """Did the plan commit anything (reference progressMade:417)?"""
    return result is not None and bool(
        result.node_update or result.node_allocation or result.deployment
        or result.deployment_updates or result.node_preemptions)


def adjust_queued_allocations(result: Optional[PlanResult],
                              queued: Dict[str, int]) -> None:
    """Decrement queued counts by what actually committed
    (reference adjustQueuedAllocations:1049)."""
    if result is None:
        return
    for allocs in result.node_allocation.values():
        for a in allocs:
            if a.task_group in queued:
                queued[a.task_group] -= 1


def update_non_terminal_allocs_to_lost(plan, tainted: Dict[str, Optional[Node]],
                                       allocs: Iterable[Allocation]) -> None:
    """On job stop/deregister, mark non-terminal allocs on down nodes lost
    (reference updateNonTerminalAllocsToLost:1078)."""
    for a in allocs:
        if a.node_id not in tainted:
            continue
        node = tainted[a.node_id]
        if node is not None and (node.draining or node.status not in
                                 (NodeStatus.DOWN, NodeStatus.DISCONNECTED)):
            continue
        if a.desired_status in ("stop", "evict") and \
                a.client_status in ("running", "pending"):
            plan.append_stopped_alloc(a, "alloc was lost since its node is down",
                                      client_status="lost")
