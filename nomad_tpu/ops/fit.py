"""Vectorized fit + scoring primitives.

Device twins of nomad_tpu.structs.resources.{allocs_fit_host,
score_fit_binpack_host, score_fit_spread_host} (reference
nomad/structs/funcs.go:166-297), lifted over the node axis: every function
here takes [N, R] matrices and returns [N] vectors, so one call covers what
the reference computes node-by-node inside BinPackIterator.Next and the
plan applier's EvaluatePool fan-out (nomad/plan_apply_pool.go).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from nomad_tpu.encode.matrixizer import RES_CPU, RES_MEM

MAX_FIT_SCORE = 18.0


def fits_after(capacity: jax.Array, used: jax.Array, demand: jax.Array) -> jax.Array:
    """bool[N]: does `demand` (f32[R]) fit on each node given current usage?
    The resource superset check of AllocsFit (funcs.go:197-203)."""
    return jnp.all(used + demand <= capacity, axis=-1)


def validate_capacity(capacity: jax.Array, used: jax.Array) -> jax.Array:
    """bool[N]: per-node totals within capacity — the plan-validation path
    (evaluateNodePlan -> AllocsFit, nomad/plan_apply.go:640)."""
    return jnp.all(used <= capacity, axis=-1)


def free_fractions(capacity: jax.Array, util: jax.Array) -> jax.Array:
    """f32[..., 2]: free cpu/mem fractions after `util`, with the
    zero-capacity convention of structs.resources._free_ratio (used>0 on
    cap<=0 -> -inf, 0 on 0 -> 1).  Broadcasts over leading axes (the bulk
    kernel evaluates an [N, M] fill grid in one call)."""
    cap = jnp.asarray(capacity)[..., (RES_CPU, RES_MEM)]
    use = jnp.asarray(util)[..., (RES_CPU, RES_MEM)]
    frac = 1.0 - use / cap
    zero_cap = cap <= 0.0
    frac = jnp.where(zero_cap & (use > 0.0), -jnp.inf, frac)
    frac = jnp.where(zero_cap & (use <= 0.0), 1.0, frac)
    return frac


def score_fit(capacity: jax.Array, util: jax.Array, spread: bool) -> jax.Array:
    """f32[N] in [0, 18]: BestFit v3 (binpack) or Worst Fit (spread) score
    (funcs.go:259-297)."""
    frac = free_fractions(capacity, util)
    total = jnp.sum(jnp.power(10.0, frac), axis=-1)
    raw = (total - 2.0) if spread else (20.0 - total)
    return jnp.clip(raw, 0.0, MAX_FIT_SCORE)
