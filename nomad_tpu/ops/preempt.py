"""Preemption selection kernel (reference: scheduler/preemption.go —
PreemptForTaskGroup:198-265, basicResourceDistance:606-624,
scoreForTaskGroup:663-680, filterAndGroupPreemptibleAllocs:682-732).

For EVERY candidate node at once: given the node's preemptible allocations
(padded candidate axis A), greedily pick evictions — lowest priority tier
first, closest resource distance within a tier, distances recomputed as the
remaining ask shrinks — until the freed+remaining resources cover the ask.
The per-node greedy loop is a lax.scan over pick steps; nodes are vmapped,
so one kernel call answers "which nodes become feasible through preemption,
and what would each evict" for the whole cluster.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

BIG = jnp.float32(3.4e38)


def _distance(needed: jax.Array, res: jax.Array) -> jax.Array:
    """basicResourceDistance vectorized over candidates: Euclidean distance
    of (ask - candidate)/ask per dimension, dimensions with zero ask
    contribute 0."""
    ask = needed[None, :]
    coord = jnp.where(ask > 0.0, (ask - res) / jnp.maximum(ask, 1e-9), 0.0)
    return jnp.sqrt(jnp.sum(coord * coord, axis=-1))


def _node_preempt(cand_res, cand_prio, cand_valid, remaining, ask,
                  max_steps: int):
    """Greedy selection for ONE node.

    cand_res:   f32[A, R] resources of preemptible allocs
    cand_prio:  i32[A]    job priority of each candidate
    cand_valid: bool[A]
    remaining:  f32[R]    node capacity minus ALL current allocs
    ask:        f32[R]    the task group's demand
    -> (met: bool, picked: bool[A])
    """
    A = cand_res.shape[0]

    def step(state, _):
        picked, needed, avail, met = state
        open_ = cand_valid & ~picked
        # lowest priority tier among open candidates
        prio_masked = jnp.where(open_, cand_prio, jnp.int32(2**31 - 1))
        min_prio = jnp.min(prio_masked)
        tier = open_ & (cand_prio == min_prio)
        dist = _distance(needed, cand_res)
        dist = jnp.where(tier, dist, BIG)
        pick = jnp.argmin(dist)
        can_pick = jnp.any(tier) & ~met
        onehot = (jnp.arange(A) == pick) & can_pick
        picked = picked | onehot
        freed = jnp.sum(jnp.where(onehot[:, None], cand_res, 0.0), axis=0)
        avail = avail + freed
        needed = needed - freed
        met = met | jnp.all(avail >= ask)
        return (picked, needed, avail, met), None

    state0 = (jnp.zeros(A, bool), ask - jnp.zeros_like(ask), remaining,
              jnp.all(remaining >= ask))
    (picked, _, avail, met), _ = jax.lax.scan(
        step, state0, None, length=max_steps)
    return met, picked, avail


@functools.partial(jax.jit, static_argnames=("max_steps",))
def preempt_for_task_group(
    cand_res: jax.Array,       # f32[N, A, R]
    cand_prio: jax.Array,      # i32[N, A]
    cand_valid: jax.Array,     # bool[N, A]
    remaining: jax.Array,      # f32[N, R] capacity - all current usage
    ask: jax.Array,            # f32[R]
    max_steps: int = 16,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (met bool[N], picked bool[N, A], avail_after f32[N, R])."""
    return jax.vmap(
        lambda r, p, v, rem: _node_preempt(r, p, v, rem, ask, max_steps)
    )(cand_res, cand_prio, cand_valid, remaining)


def net_priority(prios) -> float:
    """netPriority heuristic (rank preemption options; preemption.go:745-760):
    max priority + sum/max penalty."""
    if not prios:
        return 0.0
    mx = float(max(prios))
    if mx <= 0:
        return 0.0
    return mx + (float(sum(prios)) / mx)


def preemption_score(net_prio: float) -> float:
    """Logistic preemption score in (0,1), inflection at 2048
    (preemption.go:768-780)."""
    import math
    rate, origin = 0.0048, 2048.0
    return 1.0 / (1.0 + math.exp(rate * (net_prio - origin)))


def preempt_for_task_group_np(cand_res, cand_prio, cand_valid, remaining,
                              ask, max_steps: int = 16):
    """Numpy twin of preempt_for_task_group, used on the scheduler-worker
    host path: worker threads must not issue device work concurrently
    with the PlacementEngine's dispatcher (single-dispatch-thread
    discipline — concurrent fetches can wedge on tunneled runtimes), and
    at N x A x steps this selection is trivial host math anyway."""
    import numpy as np

    N, A, R = cand_res.shape
    picked = np.zeros((N, A), bool)
    needed = np.broadcast_to(ask, (N, R)).copy()
    avail = remaining.astype(np.float32).copy()
    met = np.all(avail >= ask, axis=-1)
    INT_MAX = np.int32(2**31 - 1)
    BIGF = np.float32(3.4e38)
    for _ in range(max_steps):
        open_ = cand_valid & ~picked
        prio_masked = np.where(open_, cand_prio, INT_MAX)
        min_prio = prio_masked.min(axis=1)                    # [N]
        tier = open_ & (cand_prio == min_prio[:, None])
        askp = needed[:, None, :]                             # [N,1,R]
        coord = np.where(askp > 0.0,
                         (askp - cand_res) / np.maximum(askp, 1e-9), 0.0)
        dist = np.sqrt((coord * coord).sum(axis=-1))          # [N, A]
        dist = np.where(tier, dist, BIGF)
        pick = dist.argmin(axis=1)                            # [N]
        can_pick = tier.any(axis=1) & ~met
        onehot = (np.arange(A)[None, :] == pick[:, None]) & can_pick[:, None]
        picked |= onehot
        freed = (cand_res * onehot[:, :, None]).sum(axis=1)
        avail += freed
        needed -= freed
        met |= np.all(avail >= ask, axis=-1)
    return met, picked, avail
