"""The dense placement engine.

One jitted `lax.scan` places every missing allocation of an evaluation:
each step scores ALL candidate nodes at once (feasibility mask -> resource
fit -> binpack/spread fit score -> anti-affinity / reschedule-penalty /
affinity / spread scoring -> normalization -> masked argmax) and the carry
threads the proposed usage matrix, per-taskgroup co-placement counts, and
per-spread-attribute value counts, so sequential placement coupling
(reference scheduler/context.go:173-210 ProposedAllocs) is preserved.

This single kernel replaces the reference's entire iterator stack for one
eval (scheduler/stack.go:344-439 GenericStack.Select and everything it
pulls: feasible.go checkers, rank.go BinPackIterator/scoring iterators,
spread.go SpreadIterator, select.go Limit/MaxScore).  Candidate subsampling
(log2-n limits, power-of-two-choices, stack.go:79-92) is intentionally
absent: the TPU scores every node densely.

Tie-breaking: the reference shuffles nodes with a seeded shuffle and takes
the first strict maximum (scheduler/util.go:464, select.go:94-116); here
argmax takes the lowest node row among equals.  Deterministic, documented
deviation — score values are parity-tested, selections may differ on exact
ties.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from nomad_tpu.analysis import recompile
from nomad_tpu.ops.fit import score_fit

# recompile-budget (nomad_tpu.analysis): every jitted kernel defined here
# is registered with the recompile registry (see module tail) so the
# bench can fail a run whose jit caches grow after warmup
_RECOMPILE_TRACKED = True

TOP_K = 5  # score_meta entries kept per placement (structs.go:10341 kheap)
# m-grid bound for the bulk kernel's per-node fill-run length: a run
# longer than the grid just continues next wave, so this trades wave
# count against the [N, M] grid's per-wave compute (the grid is the
# dominant op in a wave's body)
_FILL_GRID = 64

# The grid width is bucketed: a wave whose largest eval places count
# instances never fills a run past count, so the [N, M] grid beyond
# M = count is pure wasted compute — at the C2M-1M shape (count = 10)
# the full 64-wide grid does 4x the work of the 16-wide one for
# identical placements (runs longer than M continue next wave; the
# wavefront is M-invariant).  Two buckets keep the compile-variant
# count at 2x, covered by warmup.
FILL_GRID_BUCKETS = (16, _FILL_GRID)


def fill_grid_for(max_count: int) -> int:
    """Smallest fill-grid bucket that lets the wave's longest possible
    run complete in one wave (capped at _FILL_GRID)."""
    for m in FILL_GRID_BUCKETS:
        if max_count <= m:
            return m
    return _FILL_GRID


@jax.tree_util.register_dataclass
@dataclass
class PlaceInputs:
    """Dense inputs for one evaluation's placement pass.

    Axes: N nodes, G task groups, S placement slots, K spread attributes,
    V spread attribute values (all padded).
    """
    capacity: jax.Array        # f32[N, R]
    used: jax.Array            # f32[N, R]  proposed-usage basis
    feasible: jax.Array        # bool[G, N]
    affinity: jax.Array        # f32[G, N]
    has_affinity: jax.Array    # bool[G]
    desired_count: jax.Array   # i32[G]
    penalty: jax.Array         # bool[G, N]
    tg_count: jax.Array        # i32[G, N] existing co-placed (job, tg) allocs
    # spread tensors (K may be 0)
    spread_vidx: jax.Array     # i32[G, K, N] value index per node (V = missing)
    spread_desired: jax.Array  # f32[G, K, V+1] desired counts, -1 = no target
    spread_targeted: jax.Array # bool[G, K] targets specified vs even-spread
    spread_wfrac: jax.Array    # f32[G, K] weight / sum(|weights|)
    spread_counts: jax.Array   # f32[G, K, V+1] initial per-value counts
    spread_active: jax.Array   # bool[G, K]
    # per-(group, node) placement capacity: how many instances of the
    # group this eval may still put on the node (-1 = unlimited).  Models
    # consumable per-node resources the R-dims don't cover — device
    # instances (reference deviceAllocator free counts) — as a carry.
    place_cap: jax.Array       # i32[G, N]
    # slots
    demand: jax.Array          # f32[S, R]
    slot_tg: jax.Array         # i32[S]
    slot_active: jax.Array     # bool[S]


@jax.tree_util.register_dataclass
@dataclass
class PlaceResult:
    node: jax.Array            # i32[S] selected node row, -1 = no placement
    score: jax.Array           # f32[S] final normalized score of the pick
    fit_score: jax.Array       # f32[S] raw binpack/spread component of the pick
    nodes_evaluated: jax.Array # i32[S] feasible nodes considered
    nodes_exhausted: jax.Array # i32[S] feasible but resource-exhausted nodes
    top_nodes: jax.Array       # i32[S, TOP_K]
    top_scores: jax.Array      # f32[S, TOP_K]
    used: jax.Array            # f32[N, R] final proposed usage


def _spread_boost(inp: PlaceInputs, g: jax.Array, counts: jax.Array) -> jax.Array:
    """f32[N]: total spread score per node for task group `g` given current
    per-value counts f32[K, V+1] (reference scheduler/spread.go:116-272)."""
    vidx = inp.spread_vidx[g]          # i32[K, N]
    desired = inp.spread_desired[g]    # f32[K, V+1]
    targeted = inp.spread_targeted[g]  # bool[K]
    wfrac = inp.spread_wfrac[g]        # f32[K]
    active = inp.spread_active[g]      # bool[K]
    K, Vp1 = desired.shape
    V = Vp1 - 1                        # last slot = "missing attribute"

    missing = vidx >= V                                    # bool[K, N]
    safe_idx = jnp.minimum(vidx, V)
    cur = jnp.take_along_axis(counts, safe_idx, axis=1)    # f32[K, N]
    des = jnp.take_along_axis(desired, safe_idx, axis=1)   # f32[K, N]

    # --- targeted spread: ((desired - (used+1)) / desired) * weight_frac
    has_target = des >= 0.0
    t_boost = jnp.where(
        missing, -1.0,                                     # attr build error
        jnp.where(has_target,
                  (des - (cur + 1.0)) / jnp.maximum(des, 1e-9) * wfrac[:, None],
                  -1.0))                                   # no target: flat -1

    # --- even spread: boost from delta vs min/max of *placed* values
    placed = counts[:, :V] > 0.0                           # bool[K, V]
    any_placed = jnp.any(placed, axis=1)                   # bool[K]
    big = jnp.float32(3.4e38)
    minc = jnp.min(jnp.where(placed, counts[:, :V], big), axis=1)   # f32[K]
    maxc = jnp.max(jnp.where(placed, counts[:, :V], -big), axis=1)
    minc_ = jnp.maximum(minc, 1e-9)
    at_min = cur == minc[:, None]
    e_boost = jnp.where(
        ~at_min, (minc[:, None] - cur) / minc_[:, None],
        jnp.where((minc == maxc)[:, None], -1.0,
                  ((maxc - minc) / minc_)[:, None]))
    e_boost = jnp.where(missing, -1.0, e_boost)
    e_boost = jnp.where(any_placed[:, None], e_boost, 0.0)  # empty map -> 0

    boost = jnp.where(targeted[:, None], t_boost, e_boost)  # f32[K, N]
    return jnp.sum(jnp.where(active[:, None], boost, 0.0), axis=0)


def _place_step(inp: PlaceInputs, spread_algorithm: bool, carry, slot):
    used, tg_count, spread_counts, place_cap = carry
    g = inp.slot_tg[slot]
    d = inp.demand[slot]
    active = inp.slot_active[slot]

    feas = inp.feasible[g] & (place_cap[g] != 0)
    util = used + d
    fits = jnp.all(util <= inp.capacity, axis=-1) & feas

    # --- scoring stack (normalization = mean over appended scorers only,
    # reference rank.go ScoreNormalizationIterator)
    fit_score = score_fit(inp.capacity, util, spread_algorithm) / 18.0
    total = fit_score
    n_scorers = jnp.ones_like(fit_score)

    coll = tg_count[g].astype(jnp.float32)
    anti = -(coll + 1.0) / jnp.maximum(inp.desired_count[g].astype(jnp.float32), 1.0)
    has_coll = coll > 0.0
    total = total + jnp.where(has_coll, anti, 0.0)
    n_scorers = n_scorers + has_coll

    pen = inp.penalty[g]
    total = total - pen
    n_scorers = n_scorers + pen

    aff = inp.affinity[g]
    aff_on = inp.has_affinity[g] & (aff != 0.0)
    total = total + jnp.where(aff_on, aff, 0.0)
    n_scorers = n_scorers + aff_on

    sboost = _spread_boost(inp, g, spread_counts[g])
    sb_on = jnp.any(inp.spread_active[g]) & (sboost != 0.0)
    total = total + jnp.where(sb_on, sboost, 0.0)
    n_scorers = n_scorers + sb_on

    final = total / n_scorers
    masked = jnp.where(fits & active, final, -jnp.inf)

    sel = jnp.argmax(masked)
    ok = masked[sel] > -jnp.inf

    # --- carry updates
    sel_onehot = (jnp.arange(used.shape[0]) == sel) & ok
    used = used + jnp.where(sel_onehot[:, None], d, 0.0)
    tg_count = tg_count.at[g, sel].add(jnp.where(ok, 1, 0))
    place_cap = place_cap.at[g, sel].add(
        jnp.where(ok & (place_cap[g, sel] > 0), -1, 0))
    v = inp.spread_vidx[g, :, sel]                      # i32[K]
    Vp1 = spread_counts.shape[-1]
    upd = jax.nn.one_hot(jnp.minimum(v, Vp1 - 1), Vp1, dtype=spread_counts.dtype)
    upd = upd * (inp.spread_active[g] & (v < Vp1 - 1))[:, None] * ok
    spread_counts = spread_counts.at[g].add(upd)

    top_scores, top_nodes = jax.lax.top_k(masked, TOP_K)
    out = (
        jnp.where(ok, sel, -1).astype(jnp.int32),
        jnp.where(ok, masked[sel], 0.0),
        jnp.where(ok, fit_score[sel], 0.0),
        jnp.sum(feas & active).astype(jnp.int32),
        jnp.sum(feas & ~fits & active).astype(jnp.int32),
        top_nodes.astype(jnp.int32),
        top_scores,
    )
    return (used, tg_count, spread_counts, place_cap), out


def _pack_outputs(node, score, fit_s, n_eval, n_exh, top_n, top_s) -> jax.Array:
    """Pack the per-slot outputs into ONE f32 array [..., S, 5 + 2*TOP_K]
    so the host fetches a single leaf — on high-latency runtimes every
    device->host leaf is a ~20-35 ms round trip, so 7 leaves vs 1 is the
    difference between ~240 ms and ~25 ms per dispatch.  Integers are
    VALUE-encoded as floats (exact below 2^24) — bitcasting them would
    produce denormals that TPU hardware flushes to zero."""
    as_f = lambda x: x.astype(jnp.float32)
    return jnp.concatenate([
        as_f(node)[..., None], score[..., None], fit_s[..., None],
        as_f(n_eval)[..., None], as_f(n_exh)[..., None],
        as_f(top_n), top_s], axis=-1)


def unpack_outputs(packed: np.ndarray):
    """Host-side inverse of _pack_outputs.
    packed: f32[..., S, 5 + 2*TOP_K]."""
    as_i = lambda x: np.rint(x).astype(np.int32)
    node = as_i(packed[..., 0])
    score = packed[..., 1]
    fit_s = packed[..., 2]
    n_eval = as_i(packed[..., 3])
    n_exh = as_i(packed[..., 4])
    top_n = as_i(packed[..., 5:5 + TOP_K])
    top_s = packed[..., 5 + TOP_K:5 + 2 * TOP_K]
    return node, score, fit_s, n_eval, n_exh, top_n, top_s


@functools.partial(jax.jit, static_argnames=("spread_algorithm",))
def place_eval_packed_jit(inp: PlaceInputs, spread_algorithm: bool = False):
    """Single-eval kernel with packed output: returns (f32[S, 5+2K]
    packed outputs, f32[N, R] final usage)."""
    S = inp.demand.shape[0]
    carry0 = (inp.used, inp.tg_count, inp.spread_counts, inp.place_cap)
    step = functools.partial(_place_step, inp, spread_algorithm)
    (used, _, _, _), outs = jax.lax.scan(step, carry0, jnp.arange(S))
    return _pack_outputs(*outs), used


@functools.partial(jax.jit, static_argnames=("spread_algorithm",))
def place_eval_jit(inp: PlaceInputs, spread_algorithm: bool = False) -> PlaceResult:
    """Place all slots of one evaluation.  Shapes are static; callers bucket
    N/G/S/K/V so the jit cache stays small."""
    S = inp.demand.shape[0]
    carry0 = (inp.used, inp.tg_count, inp.spread_counts, inp.place_cap)
    step = functools.partial(_place_step, inp, spread_algorithm)
    (used, _, _, _), outs = jax.lax.scan(step, carry0, jnp.arange(S))
    node, score, fit_s, n_eval, n_exh, top_n, top_s = outs
    return PlaceResult(node=node, score=score, fit_score=fit_s,
                       nodes_evaluated=n_eval, nodes_exhausted=n_exh,
                       top_nodes=top_n, top_scores=top_s, used=used)


# --------------------------------------------------------------------------
# Packed H2D transport.
#
# The D2H side already ships ONE leaf (_pack_outputs) because every
# device<->host leaf on a high-latency runtime is its own ~20-35 ms round
# trip; the H2D side of a batch dispatch used to ship an ~18-leaf
# per-eval-field pytree and paid the same per-leaf tax 18x.  Here every
# eval's placement inputs flatten into two f32 vectors:
#
#   heavy[Lh]: the G x N-scale tensors (feasibility, affinity, penalty,
#       co-placement counts, place capacity, spread programs).  These are
#       functions of (job version, cluster epoch, existing allocs) and are
#       IDENTICAL across evals of the same job state, so the engine
#       content-addresses them into a device-resident cache — a cache hit
#       ships zero bytes (SURVEY.md §7 "Host<->device latency": keep the
#       big tensors resident, ship only deltas).
#   light[Ll]: the per-eval slot demand/targets and sparse usage deltas —
#       KBs, always shipped, concatenated with the f32[N, R] usage basis
#       into one dyn buffer = ONE device_put per dispatch.
#
# Integers are VALUE-encoded as f32 (exact below 2^24); bitcasting would
# produce denormals that TPU hardware flushes to zero.
# --------------------------------------------------------------------------

def heavy_dims(inp: PlaceInputs):
    """(G, N, K, Vp1) of one eval's inputs."""
    G, N = inp.feasible.shape
    K = inp.spread_wfrac.shape[1]
    Vp1 = inp.spread_desired.shape[2]
    return G, N, K, Vp1


_HEAVY_FIELDS = ("feasible", "affinity", "penalty", "tg_count", "place_cap",
                 "spread_vidx", "spread_desired", "spread_counts",
                 "has_affinity", "desired_count", "spread_targeted",
                 "spread_wfrac", "spread_active")


def pack_heavy(inp: PlaceInputs) -> np.ndarray:
    """Flatten one eval's G x N-scale tensors into one f32 vector."""
    return np.concatenate(
        [np.asarray(getattr(inp, f), np.float32).ravel()
         for f in _HEAVY_FIELDS])


def heavy_digest(inp: PlaceInputs) -> bytes:
    """Content fingerprint of the heavy block WITHOUT materializing the
    packed array (the common case is a cache hit)."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    for f in _HEAVY_FIELDS:
        h.update(np.ascontiguousarray(getattr(inp, f)).tobytes())
    return h.digest()


def _unpack_heavy(h: jax.Array, G: int, N: int, K: int, Vp1: int):
    """In-kernel inverse of pack_heavy; returns a field dict."""
    o = 0
    def take(n, shape):
        nonlocal o
        v = h[o:o + n].reshape(shape)
        o += n
        return v
    return dict(
        feasible=take(G * N, (G, N)) > 0.5,
        affinity=take(G * N, (G, N)),
        penalty=take(G * N, (G, N)) > 0.5,
        tg_count=take(G * N, (G, N)).astype(jnp.int32),
        place_cap=take(G * N, (G, N)).astype(jnp.int32),
        spread_vidx=take(G * K * N, (G, K, N)).astype(jnp.int32),
        spread_desired=take(G * K * Vp1, (G, K, Vp1)),
        spread_counts=take(G * K * Vp1, (G, K, Vp1)),
        has_affinity=take(G, (G,)) > 0.5,
        desired_count=take(G, (G,)).astype(jnp.int32),
        spread_targeted=take(G * K, (G, K)) > 0.5,
        spread_wfrac=take(G * K, (G, K)),
        spread_active=take(G * K, (G, K)) > 0.5,
    )


def light_len(S: int, R: int, D: int) -> int:
    return S * (R + 2) + D * (R + 1)


def pack_light(inp: PlaceInputs, deltas, D: int,
               S: Optional[int] = None) -> np.ndarray:
    """Flatten one eval's slot tensors + sparse usage deltas.  `deltas` is
    [(row, f32[R])]; inactive delta slots encode row = N (dropped by the
    in-kernel scatter's mode='drop').  `S` pads the slot axis to a
    canonical bucket (padded slots are inactive) so the engine's compile
    variants stay fixed regardless of per-eval slot counts."""
    S_in, R = inp.demand.shape
    S = S_in if S is None else S
    N = inp.feasible.shape[1]
    out = np.zeros(light_len(S, R, D), np.float32)
    o = 0
    out[o:o + S_in * R] = np.asarray(inp.demand, np.float32).ravel()
    o += S * R
    out[o:o + S_in] = np.asarray(inp.slot_tg, np.float32); o += S
    out[o:o + S_in] = np.asarray(inp.slot_active, np.float32); o += S
    rows = np.full(D, N, np.float32)
    vals = np.zeros((D, R), np.float32)
    for d, (row, vec) in enumerate(deltas[:D]):
        rows[d] = row
        vals[d] = vec
    out[o:o + D] = rows; o += D
    out[o:o + D * R] = vals.ravel()
    return out


def _unpack_light(l: jax.Array, S: int, R: int, D: int):
    o = 0
    def take(n, shape):
        nonlocal o
        v = l[o:o + n].reshape(shape)
        o += n
        return v
    demand = take(S * R, (S, R))
    slot_tg = take(S, (S,)).astype(jnp.int32)
    slot_active = take(S, (S,)) > 0.5
    delta_rows = take(D, (D,)).astype(jnp.int32)
    delta_vals = take(D * R, (D, R))
    return demand, slot_tg, slot_active, delta_rows, delta_vals


@functools.partial(jax.jit, static_argnames=("dims", "spread_algorithm"))
def place_batch_packed_jit(capacity: jax.Array,     # f32[N, R]
                           used0: jax.Array,        # f32[N, R] (device)
                           heavy: tuple,            # E x f32[Lh] (device)
                           dyn: jax.Array,          # f32[E*Ll]
                           dims: tuple,             # (G, N, K, Vp1, S, D)
                           spread_algorithm: bool = False):
    """Chained batch placement over the packed transport: `heavy` is a
    tuple of E device-resident per-eval blocks (cache hits ship nothing),
    `used0` the device-resident usage basis (dirty rows shipped by the
    engine), `dyn` the per-eval light blocks.

    Chaining (a `lax.scan` over the eval axis, carrying f32[N, R] usage)
    makes the batch exactly equivalent to sequential worker processing:
    eval e+1 scores against usage that includes eval e's placements, so
    concurrently submitted plans never conflict on resources — any commit
    order of the resulting plans fits, because chained usage is
    cumulative.  This replaces the reference's optimistic
    conflict-then-retry dance (nomad/worker.go:81-85 concurrent workers +
    plan_apply.go partial commit) with a conflict-free device-side
    pipeline; the serialized plan applier still re-validates as defense
    in depth."""
    G, N, K, Vp1, S, D = dims
    R = capacity.shape[1]
    E = len(heavy)
    hstack = jnp.stack(heavy)
    light = dyn.reshape(E, -1)

    def eval_step(used, hl):
        h, l = hl
        f = _unpack_heavy(h, G, N, K, Vp1)
        demand, slot_tg, slot_active, delta_rows, delta_vals = \
            _unpack_light(l, S, R, D)
        used = used.at[delta_rows].add(delta_vals, mode="drop")
        inp = PlaceInputs(capacity=capacity, used=used, demand=demand,
                          slot_tg=slot_tg, slot_active=slot_active, **f)
        carry0 = (used, f["tg_count"], f["spread_counts"], f["place_cap"])
        step = functools.partial(_place_step, inp, spread_algorithm)
        (used_f, _, _, _), outs = jax.lax.scan(step, carry0, jnp.arange(S))
        return used_f, _pack_outputs(*outs)

    used_final, packed = jax.lax.scan(eval_step, used0, (hstack, light))
    return packed, used_final


def bulk_wave_grid(capacity, used, demand, feasible, affinity,
                   has_affinity, desired_f, penalty, coll,
                   spread_algorithm: bool, fill_grid: int = _FILL_GRID):
    """The [N, M] per-wave fill/scoring grid shared by the single-device
    (`_bulk_loop`) and node-sharded (parallel.sharded) bulk kernels —
    column m is every node's score/fitness with m more instances placed
    on it.  Returns (ms f32[M], fits_m bool[N, M], score_m f32[N, M]).
    Operates on whatever node slice it is given (a shard passes its
    local rows); MUST stay the single source of truth for the bulk
    scoring stack or sharded/single-device placement parity breaks."""
    M = fill_grid
    ms = jnp.arange(1, M + 1, dtype=jnp.float32)
    util_m = used[:, None, :] + ms[None, :, None] * demand    # [N, M, R]
    fits_m = (jnp.all(util_m <= capacity[:, None, :], axis=-1)
              & feasible[:, None])
    fit_m = score_fit(capacity[:, None, :], util_m,
                      spread_algorithm) / 18.0                 # [N, M]
    coll_m = coll[:, None].astype(jnp.float32) + ms[None, :] - 1.0
    total_m = fit_m
    n_sc = jnp.ones_like(fit_m)
    anti_m = -(coll_m + 1.0) / jnp.maximum(desired_f, 1.0)
    has_coll_m = coll_m > 0.0
    total_m = total_m + jnp.where(has_coll_m, anti_m, 0.0)
    n_sc = n_sc + has_coll_m
    total_m = total_m - penalty[:, None]
    n_sc = n_sc + penalty[:, None]
    aff_on = has_affinity & (affinity != 0.0)                  # [N]
    total_m = total_m + jnp.where(aff_on[:, None], affinity[:, None], 0.0)
    n_sc = n_sc + aff_on[:, None]
    return ms, fits_m, total_m / n_sc


def bulk_run_lengths(ms, fits_m, score_m, second):
    """Per-node greedy fill runs from the wave grid: leading m's where
    the node still fits and score_m strictly beats `second` (the best
    wave-start score among the OTHERS); m=1 is the FORCED placement —
    once a node is argmax (by score or lowest-row tie-break), greedy
    places on it regardless of its post-score."""
    ok_m = fits_m & ((score_m > second[:, None]) | (ms[None, :] == 1.0))
    return jnp.sum(jnp.cumprod(ok_m.astype(jnp.int32), axis=1),
                   axis=1).astype(jnp.int32)


def _bulk_scores(capacity, used, demand, feasible, affinity, has_affinity,
                 desired, penalty, coll, spread_algorithm: bool):
    """Composite per-node score for one task group with spreads inactive —
    exactly _place_step's scoring stack minus the spread scorer."""
    util = used + demand
    fits = jnp.all(util <= capacity, axis=-1) & feasible
    fit = score_fit(capacity, util, spread_algorithm) / 18.0
    total = fit
    n_scorers = jnp.ones_like(fit)
    anti = -(coll.astype(jnp.float32) + 1.0) / jnp.maximum(
        jnp.asarray(desired).astype(jnp.float32), 1.0)
    has_coll = coll > 0
    total = total + jnp.where(has_coll, anti, 0.0)
    n_scorers = n_scorers + has_coll
    total = total - penalty
    n_scorers = n_scorers + penalty
    aff_on = has_affinity & (affinity != 0.0)
    total = total + jnp.where(aff_on, affinity, 0.0)
    n_scorers = n_scorers + aff_on
    final = total / n_scorers
    return jnp.where(fits, final, -jnp.inf), fits


def _bulk_loop(capacity, used0, feasible, affinity, has_affinity, desired,
               penalty, coll0, demand, count,
               spread_algorithm: bool, max_waves: int,
               fill_grid: int = _FILL_GRID):
    """The wavefront placement loop shared by the single-eval
    (`place_bulk_jit`) and batched (`place_bulk_batch_jit`) kernels.
    Places `count` IDENTICAL slots of one task group (spreads inactive)
    in O(waves) device steps instead of O(count) scan steps — the
    C2M-scale path (SURVEY.md §7 "slot-batching smarter than a 100K-step
    scan").

    Exactness vs the sequential scan: scoring is row-independent, so
    sequential greedy fills nodes in contiguous "runs" — it keeps
    picking node i while score_i(after m instances) strictly exceeds
    every other node's current score — and the FIRST placement on a node
    that became argmax (by score or the lowest-row tie-break) is forced
    regardless of its post-score.  Each wave computes, for EVERY node,
    that run length on a vectorized [N, M] fill grid (anti-affinity
    decays linearly, binpack fit rises as the node fills; non-monotone
    dips are honored because the run counts LEADING m's only, and
    `second_i` uses wave-start scores of the others, which can only
    UNDER-count a run — the next wave catches the remainder), then
    places the runs of the active wave set in greedy order
    (score desc, row asc — the argmax tie-break), cumulatively capped by
    the remaining count:

      * strict set (cur > s* = best post-placement score anywhere): the
        nodes greedy provably drains before revisiting anyone;
      * else the tie set (cur == global max): every tied node places at
        least one instance (greedy visits each in row order before any
        score re-enters the tie) plus its fill run.

    A uniform cluster thus fills in O(count / (nodes x per-node run))
    waves — one wave in the common fresh-world case — instead of one
    node-fill per wave.

    max_waves is a runaway guard only — it must exceed any realistic
    count, because packed clusters can degrade to one placement per wave
    and an exhausted guard silently strands unplaced slots.

    Returns (used_f f32[N, R], coll_f i32[N], assign i32[N], placed i32).
    """
    N = capacity.shape[0]
    desired_f = jnp.asarray(desired).astype(jnp.float32)

    def cond(c):
        used, coll, placed, assign, stuck, waves = c
        return (placed < count) & ~stuck & (waves < max_waves)

    def body(c):
        used, coll, placed, assign, stuck, waves = c
        # ONE [N, M] scoring grid per wave (bulk_wave_grid, shared with
        # the node-sharded kernel): m=1 ("place one more now") is the
        # wave-start score, m=2 each node's own "+1" world (scoring is
        # row-independent, so this evaluates the post-placement score of
        # every node at once), and the leading columns give the per-node
        # fill runs.
        ms, fits_m, score_m = bulk_wave_grid(
            capacity, used, demand, feasible, affinity, has_affinity,
            desired_f, penalty, coll, spread_algorithm, fill_grid)

        fits = fits_m[:, 0]
        cur = jnp.where(fits, score_m[:, 0], -jnp.inf)
        any_fit = jnp.any(fits)
        s_star = jnp.max(jnp.where(fits_m[:, 1], score_m[:, 1], -jnp.inf))

        strict = fits & (cur > s_star)
        top2 = jax.lax.top_k(cur, 2)[0]
        tie = fits & (cur == top2[0])
        wave = jnp.where(jnp.any(strict), strict, tie)

        second = jnp.where(cur == top2[0], top2[1], top2[0])   # [N]
        run = bulk_run_lengths(ms, fits_m, score_m, second)

        # greedy-order the wave's runs (score desc, stable -> row asc
        # among ties) and cap cumulatively at the remaining count
        base = jnp.where(wave, run, 0)
        remaining = count - placed
        order = jnp.argsort(jnp.where(wave, -cur, jnp.inf))
        base_sorted = base[order]
        prefix = jnp.cumsum(base_sorted) - base_sorted
        alloc_sorted = jnp.clip(remaining - prefix, 0, base_sorted)
        per_node = jnp.zeros(N, jnp.int32).at[order].set(alloc_sorted)

        used = used + per_node[:, None].astype(jnp.float32) * demand
        coll = coll + per_node
        assign = assign + per_node
        placed = placed + jnp.sum(per_node)
        stuck = ~any_fit
        return (used, coll, placed, assign, stuck, waves + 1)

    c0 = (used0, coll0, jnp.int32(0), jnp.zeros(N, jnp.int32),
          jnp.array(False), jnp.int32(0))
    used_f, coll_f, placed, assign, _, waves = \
        jax.lax.while_loop(cond, body, c0)
    return used_f, coll_f, assign, placed, waves


def _bulk_tail(capacity, used_f, coll_f, feasible, affinity, has_affinity,
               desired, penalty, demand, spread_algorithm: bool):
    """Final scores + eval/exhaustion counts after a wavefront run."""
    final_scores, fits_f = _bulk_scores(capacity, used_f, demand, feasible,
                                        affinity, has_affinity, desired,
                                        penalty, coll_f, spread_algorithm)
    n_eval = jnp.sum(feasible).astype(jnp.int32)
    n_exh = jnp.sum(feasible & ~fits_f).astype(jnp.int32)
    return final_scores, n_eval, n_exh


@functools.partial(jax.jit,
                   static_argnames=("spread_algorithm", "max_waves",
                                    "fill_grid"))
def place_bulk_jit(capacity: jax.Array,    # f32[N, R]
                   used0: jax.Array,       # f32[N, R]
                   feasible: jax.Array,    # bool[N]
                   affinity: jax.Array,    # f32[N]
                   has_affinity: bool,
                   desired: jax.Array,     # i32 scalar (tg count)
                   penalty: jax.Array,     # bool[N]
                   coll0: jax.Array,       # i32[N] existing co-placements
                   demand: jax.Array,      # f32[R]
                   count: jax.Array,       # i32 scalar: instances to place
                   spread_algorithm: bool = False,
                   max_waves: int = 65536,
                   fill_grid: int = _FILL_GRID):
    """Single-eval wavefront placement (see `_bulk_loop` for semantics).

    Returns one packed f32[N, R+3] leaf (one D2H round trip): cols [0,R)
    used, col R assign, col R+1 scores, col R+2 scalars in rows 0-2.
    Integers are value-encoded (exact below 2^24); bitcast encodings
    become denormals that TPU hardware flushes to zero."""
    used_f, coll_f, assign, placed, waves = _bulk_loop(
        capacity, used0, feasible, affinity, has_affinity, desired,
        penalty, coll0, demand, count, spread_algorithm, max_waves,
        fill_grid)
    final_scores, n_eval, n_exh = _bulk_tail(
        capacity, used_f, coll_f, feasible, affinity, has_affinity,
        desired, penalty, demand, spread_algorithm)
    as_f = lambda x: x.astype(jnp.float32)
    scalars = jnp.zeros(capacity.shape[0], jnp.float32) \
        .at[0].set(as_f(placed)).at[1].set(as_f(n_eval)) \
        .at[2].set(as_f(n_exh)).at[3].set(as_f(waves))
    return jnp.concatenate([used_f, as_f(assign)[:, None],
                            final_scores[:, None], scalars[:, None]],
                           axis=-1)


# --- batched bulk transport (same packed single-leaf scheme as
# place_batch_packed_jit: heavy = per-eval node-axis tensors, content-
# addressed device-side; light = per-eval scalars + sparse deltas) -------

def pack_bulk_heavy(feasible, affinity, penalty, coll0) -> np.ndarray:
    """f32[4N]: one bulk eval's node-axis tensors."""
    return np.concatenate([
        np.asarray(feasible, np.float32),
        np.asarray(affinity, np.float32),
        np.asarray(penalty, np.float32),
        np.asarray(coll0, np.float32)])


def bulk_heavy_digest(feasible, affinity, penalty, coll0) -> bytes:
    """Content fingerprint of one bulk request's node-axis tensors.
    All-zero fields (the common fresh-job case: no affinities, no
    penalties, no existing co-placements) hash as a 1-byte marker, and
    bools hash bit-packed — hashing dominated the device-cache HIT path
    at C2M-1M rates otherwise."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    h.update(np.packbits(np.asarray(feasible, bool)).tobytes())
    # tag bytes frame each variable-length segment: without them,
    # (full||marker) and (marker||full) byte streams could collide
    for tag, a in ((b"\x01", affinity), (b"\x02", coll0)):
        if np.any(a):
            h.update(tag + b"F")
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            h.update(tag + b"0")
    if np.any(penalty):
        h.update(b"\x03F")
        h.update(np.packbits(np.asarray(penalty, bool)).tobytes())
    else:
        h.update(b"\x030")
    return h.digest()


def bulk_light_len(R: int, D: int) -> int:
    return 3 + R + D * (R + 1)


def pack_bulk_light(has_affinity, desired, count, demand, deltas,
                    N: int, D: int) -> np.ndarray:
    R = demand.shape[0]
    out = np.empty(bulk_light_len(R, D), np.float32)
    out[0] = float(bool(has_affinity))
    out[1] = float(desired)
    out[2] = float(count)
    out[3:3 + R] = np.asarray(demand, np.float32)
    rows = np.full(D, N, np.float32)
    vals = np.zeros((D, R), np.float32)
    for d, (row, vec) in enumerate(deltas[:D]):
        rows[d] = row
        vals[d] = vec
    out[3 + R:3 + R + D] = rows
    out[3 + R + D:] = vals.ravel()
    return out


# sparse bulk output: assignments of a count<=SPARSE_CAP eval fit in
# SPARSE_CAP (row, count) pairs + the scores AT those rows.  A dense
# [N] assign+scores row is ~2N floats of D2H per eval — on a
# high-latency/low-bandwidth runtime link that transfer, not the
# kernel, dominated C2M-1M serving.
SPARSE_CAP = 128


def _place_bulk_batch(capacity: jax.Array,      # f32[N, R]
                      used0: jax.Array,         # f32[N, R] (device basis)
                      heavy: jax.Array,         # f32[E, 4N] (device, stacked
                      #   OUTSIDE jit: a 128-element tuple argument
                      #   costs ~0.4s/call in pjit arg processing)
                      dyn: jax.Array,           # f32[E*Ll] light blocks
                      D: int,
                      sparse_out: bool = False,
                      spread_algorithm: bool = False,
                      max_waves: int = 65536,
                      fill_grid: int = _FILL_GRID,
                      exact_out: bool = False):
    """Chained batch of E wavefront bulk evals in ONE dispatch: a
    `lax.scan` over the eval axis carries the usage matrix, each step
    runs `_bulk_loop` (the O(waves) wavefront placement), so eval e+1
    scores against usage including eval e's placements — identical to
    sequential bulk processing but paying one transfer round trip per
    *batch*.  Each eval's sparse deltas (its own plan's stops /
    preplacements) are scoped to that eval only: they apply before its
    wavefront and are backed out of the carry after, matching the
    serialized bulk path where uncommitted stops of one eval are never
    visible to another (only *placements* chain forward, mirroring the
    engine's in-flight overlay).

    used0 is a DEVICE-RESIDENT basis (engine ships dirty rows only).
    Returns (packed, used_final device-resident).  packed per eval:
    dense [2N+4] (assign[N], scores[N], placed/n_eval/n_exh/waves) or,
    with sparse_out, [3*SPARSE_CAP+4] (rows, counts, row_scores,
    scalars) — for count <= SPARSE_CAP only.

    Jitted twice below: `place_bulk_batch_jit` (plain) and
    `place_bulk_batch_donate_jit` (donate_argnums=(1,): the `used0`
    carry buffer is donated and the caller adopts the carry output as
    the new resident basis via world.loan_basis/adopt_basis — the carry
    never re-uploads).

    `exact_out` (the donation path) additionally threads an EXACT
    rank-1 reconstruction of the basis — `used0 + sum_e assign_e *
    demand_e`, one fused multiply-add per eval, the same op sequence as
    world.apply_rank1's host/device scatters — and returns (packed,
    used_final, used_exact).  The scan's own carry accumulates per-wave
    partial placements (multiple f32 adds per node), which drifts
    bitwise from the rank-1 form; scoring must keep the drifted chain
    carry (placement parity with the non-donated path), while the
    ADOPTED basis must stay bitwise in lockstep with the host snapshot
    that apply_rank1_host maintains — hence two carries."""
    N, R = capacity.shape
    E = heavy.shape[0]
    hstack = heavy
    light = dyn.reshape(E, -1)

    def eval_step(carry, hl):
        used, exact = carry if exact_out else (carry, None)
        h, l = hl
        feasible = h[:N] > 0.5
        affinity = h[N:2 * N]
        penalty = h[2 * N:3 * N] > 0.5
        coll0 = h[3 * N:].astype(jnp.int32)
        has_aff = l[0] > 0.5
        desired = l[1].astype(jnp.int32)
        count = l[2].astype(jnp.int32)
        demand = l[3:3 + R]
        delta_rows = l[3 + R:3 + R + D].astype(jnp.int32)
        delta_vals = l[3 + R + D:].reshape(D, R)
        delta_mat = jnp.zeros_like(used).at[delta_rows].add(
            delta_vals, mode="drop")
        used_f, coll_f, assign, placed, waves = _bulk_loop(
            capacity, used + delta_mat, feasible, affinity, has_aff,
            desired, penalty, coll0, demand, count, spread_algorithm,
            max_waves, fill_grid)
        scores, n_eval, n_exh = _bulk_tail(
            capacity, used_f, coll_f, feasible, affinity, has_aff,
            desired, penalty, demand, spread_algorithm)
        as_f = lambda x: x.astype(jnp.float32)
        scalars = jnp.stack([as_f(placed), as_f(n_eval), as_f(n_exh),
                             as_f(waves)])
        if sparse_out:
            # scatter-compaction, NOT top_k: a sort over the node axis
            # per chained eval (~4ms at 16K rows) would dominate the
            # whole wavefront.  Nonzero-assign rows get consecutive
            # slots via a prefix count; everything else lands in the
            # dropped overflow slot.
            mask = assign > 0
            pos = jnp.cumsum(mask) - 1
            tgt = jnp.where(mask, jnp.minimum(pos, SPARSE_CAP),
                            SPARSE_CAP)
            rows_o = jnp.full(SPARSE_CAP + 1, N, jnp.float32) \
                .at[tgt].set(jnp.arange(N, dtype=jnp.float32))
            counts_o = jnp.zeros(SPARSE_CAP + 1, jnp.float32) \
                .at[tgt].set(as_f(assign))
            scores_o = jnp.zeros(SPARSE_CAP + 1, jnp.float32) \
                .at[tgt].set(scores)
            # overflow slot holds junk from every masked-out row; the
            # sliced-off SPARSE_CAP+1 slot absorbs it
            out = jnp.concatenate([
                rows_o[:SPARSE_CAP], counts_o[:SPARSE_CAP],
                scores_o[:SPARSE_CAP], scalars])
        else:
            out = jnp.concatenate([as_f(assign), scores, scalars])
        new_used = used_f - delta_mat
        if exact_out:
            return (new_used, exact + as_f(assign)[:, None] * demand), out
        return new_used, out

    carry0 = (used0, used0) if exact_out else used0
    carry_f, packed = jax.lax.scan(eval_step, carry0, (hstack, light))
    if exact_out:
        used_final, used_exact = carry_f
        return packed, used_final, used_exact
    return packed, carry_f


_BULK_BATCH_STATICS = ("D", "sparse_out", "spread_algorithm",
                       "max_waves", "fill_grid", "exact_out")
place_bulk_batch_jit = jax.jit(
    _place_bulk_batch, static_argnames=_BULK_BATCH_STATICS)
place_bulk_batch_donate_jit = jax.jit(
    _place_bulk_batch, static_argnames=_BULK_BATCH_STATICS,
    donate_argnums=(1,))

# Loan/adopt protocol for every donate_argnums site in this module
# (the donation-safety checker fails an undeclared donating jit).
_DONATE_PROTOCOL = {
    "place_bulk_batch_donate_jit":
        "arg 1 (used0) is the loaned usage basis: the engine takes it "
        "via world.loan_basis(), must not read it after dispatch, and "
        "adopts the exact carry via world.adopt_basis() — or "
        "invalidates the basis on a failed dispatch",
}


def unpack_bulk_batch(packed: np.ndarray, n_rows: int,
                      sparse: bool = False):
    """Host inverse of place_bulk_batch_jit's per-eval rows (both
    formats; sparse rows densify host-side — numpy, no transfer):
    returns (assign i32[E, N], scores f32[E, N], placed i32[E],
    n_eval i32[E], n_exh i32[E], waves i32[E]).  Dense scores default
    to -inf at unassigned rows in the sparse format (consumers only
    read scores at assigned rows)."""
    E, W = packed.shape
    s = np.rint(packed[:, -4:]).astype(np.int32)
    if sparse:
        rows = np.rint(packed[:, :SPARSE_CAP]).astype(np.int64)
        counts = np.rint(
            packed[:, SPARSE_CAP:2 * SPARSE_CAP]).astype(np.int32)
        rscores = packed[:, 2 * SPARSE_CAP:3 * SPARSE_CAP]
        assign = np.zeros((E, n_rows), np.int32)
        scores = np.full((E, n_rows), -np.inf, np.float32)
        e_idx = np.repeat(np.arange(E), SPARSE_CAP)
        r_idx = rows.ravel()
        c = counts.ravel()
        keep = c > 0
        assign[e_idx[keep], r_idx[keep]] = c[keep]
        scores[e_idx[keep], r_idx[keep]] = rscores.ravel()[keep]
        return assign, scores, s[:, 0], s[:, 1], s[:, 2], s[:, 3]
    N = (W - 4) // 2
    assign = np.rint(packed[:, :N]).astype(np.int32)
    scores = packed[:, N:2 * N]
    return assign, scores, s[:, 0], s[:, 1], s[:, 2], s[:, 3]


def unpack_bulk(packed: np.ndarray):
    """Host inverse of place_bulk_jit's packed leaf: returns
    (assign i32[N], placed, n_eval, n_exh, scores f32[N], waves,
    used f32[N,R]) — `used` stays last so `*_, used` callers survive
    field additions."""
    R = packed.shape[1] - 3
    used = packed[:, :R]
    assign = np.rint(packed[:, R]).astype(np.int32)
    scores = packed[:, R + 1]
    s = np.rint(packed[:4, R + 2]).astype(np.int32)
    return assign, int(s[0]), int(s[1]), int(s[2]), scores, int(s[3]), used


def place_eval(inp: PlaceInputs, spread_algorithm: bool = False) -> PlaceResult:
    """Convenience host wrapper returning numpy-backed results.

    All outputs come back in ONE single-leaf D2H transfer (the packed
    output array); the f32[N, R] `used` matrix stays device-resident (no
    caller reads it on host — transferring it per eval dominated e2e wall
    time on high-latency runtimes).
    """
    packed, used = place_eval_packed_jit(inp,
                                         spread_algorithm=spread_algorithm)
    node, score, fit_s, n_eval, n_exh, top_n, top_s = unpack_outputs(
        jax.device_get(packed))
    return PlaceResult(node=node, score=score, fit_score=fit_s,
                       nodes_evaluated=n_eval, nodes_exhausted=n_exh,
                       top_nodes=top_n, top_scores=top_s, used=used)


# every jit cache in this module, named for the recompile budget: a
# post-warmup growth of any of these is a shape-bucketing regression
recompile.register("place.eval_packed", place_eval_packed_jit)
recompile.register("place.eval", place_eval_jit)
recompile.register("place.batch_packed", place_batch_packed_jit)
recompile.register("place.bulk", place_bulk_jit)
recompile.register("place.bulk_batch", place_bulk_batch_jit)
recompile.register("place.bulk_batch_donate", place_bulk_batch_donate_jit)
