"""Device kernels: the scheduler hot path as dense JAX ops.

Replaces the reference's per-node lazy iterator chain
(scheduler/rank.go:193-551 BinPackIterator, scheduler/feasible.go checkers,
scheduler/select.go Limit/MaxScore) with batched fixed-shape kernels:

- fit.py        vectorized AllocsFit + BestFit-v3 scoring over the node axis
- place.py      the placement engine: lax.scan over placement slots with a
                proposed-usage carry, scoring every node at every step
- constraints.py device-side constraint-program evaluation over hashed
                attribute code matrices (host numpy twin lives in
                scheduler/feasible.py)
- preempt.py    masked greedy preemption selection (lax.while_loop)
"""

from nomad_tpu.ops.fit import (
    fits_after,
    free_fractions,
    score_fit,
    validate_capacity,
)
from nomad_tpu.ops.place import PlaceResult, place_eval, place_eval_jit

__all__ = [k for k in dir() if not k.startswith("_")]
