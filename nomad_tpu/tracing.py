"""Dapper-style sampled distributed tracing for the placement spine.

A `Tracer` makes one sampling decision at ingress; sampled requests get a
trace context — ``{"t": trace_id, "s": parent_span_id, "b": 1}`` — that
rides RPC args end-to-end under the reserved key `TRACE_KEY`.  Absence of
the key IS the unsampled state: no per-request flag, no allocation.  The
tracer is installed process-wide (`install()`) or picked up from the
environment at import, chaos-layer style:

    NOMAD_TPU_TRACE=1 NOMAD_TPU_TRACE_SAMPLE=0.01 nomad agent ...

Instrumentation sites pay exactly one module-attribute load + ``is not
None`` branch when tracing is off (the chaos idiom), and only sampled
requests allocate spans.  Span timestamps are captured at propose or
observe time only — never inside the FSM cone, so replicas replay to
byte-identical state (see nomad_tpu.analysis.fsm_determinism).  The raft
spine is traced via side tables keyed off the log index on the proposing
node; trace context never rides in log payloads.

Spans land in a bounded ring `SpanStore` per server (`store_for(node)`),
queried through `/v1/traces` + `/v1/traces/<trace_id>` and exportable as
Chrome-trace JSON (`chrome_trace()`) for Perfetto.
"""
from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from nomad_tpu import knobs
from nomad_tpu.analysis import race

# reserved RPC-args key the context rides under; handlers pop it before
# dispatch so endpoint code never sees it in its own args
TRACE_KEY = "_trace"


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start", "duration", "node", "attrs")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, start: float, duration: float = 0.0,
                 node: str = "", attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.duration = duration
        self.node = node
        self.attrs = attrs or {}

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start": self.start, "duration": self.duration,
                "node": self.node, "attrs": self.attrs}


class SpanStore:
    """Bounded ring of finished spans for one server.  Shared by every
    request thread on that server, so the ring is lock-guarded and traced
    by the happens-before detector like the event broker's queues."""

    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_spans"})
    _RACE_TRACED = {"_spans": "_lock"}

    def __init__(self, limit: int = 4096):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=limit)

    def add(self, span: Span) -> None:
        with self._lock:
            race.write("SpanStore._spans", self)
            self._spans.append(span)

    def snapshot(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            race.read("SpanStore._spans", self)
            if trace_id is None:
                return list(self._spans)
            return [s for s in self._spans if s.trace_id == trace_id]

    def __len__(self) -> int:
        with self._lock:
            race.read("SpanStore._spans", self)
            return len(self._spans)


class Tracer:
    """Process-wide trace plane: sampling, span-id allocation, per-node
    span stores, and the propose-time side tables that let the broker
    wait and the raft pipeline be timed without touching the FSM cone."""

    # evals noted at propose time but never dequeued (leadership churn,
    # failed applies) must not leak; the table is bounded and evicts
    # oldest-first
    _NOTE_LIMIT = 4096

    def __init__(self, sample_rate: float = 1.0, seed: int = 0,
                 store_limit: int = 4096):
        self.sample_rate = float(sample_rate)
        self.store_limit = int(store_limit)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._stores: Dict[str, SpanStore] = {}
        # eval_id -> (ctx, enqueue_ts): written at propose time (outside
        # the FSM), read at broker dequeue to emit the queue-wait span
        self._eval_notes: Dict[str, Tuple[dict, float]] = {}

    # ------------------------------------------------------------- sampling

    def _new_id(self) -> str:
        with self._lock:
            return "%016x" % self._rng.getrandbits(64)

    def new_context(self) -> Optional[dict]:
        """One sampling decision at ingress; None means unsampled and the
        request proceeds with zero further tracing work anywhere."""
        with self._lock:
            if self._rng.random() >= self.sample_rate:
                return None
            return {"t": "%016x" % self._rng.getrandbits(64),
                    "s": "", "b": 1}

    # ------------------------------------------------------------- spans

    def start(self, ctx: dict, name: str, node: str = "") -> Span:
        return Span(trace_id=ctx["t"], span_id=self._new_id(),
                    parent_id=ctx.get("s", ""), name=name,
                    start=time.time(), node=node)

    def finish(self, span: Span, end: Optional[float] = None) -> None:
        span.duration = max(0.0, (time.time() if end is None else end)
                            - span.start)
        self.store_for(span.node).add(span)

    def emit(self, ctx: dict, name: str, start: float, end: float,
             node: str = "", **attrs) -> Span:
        """Record a finished span from externally captured timestamps
        (observe-time emission for work that already happened)."""
        span = Span(trace_id=ctx["t"], span_id=self._new_id(),
                    parent_id=ctx.get("s", ""), name=name, start=start,
                    duration=max(0.0, end - start), node=node,
                    attrs=attrs or None)
        self.store_for(node).add(span)
        return span

    @staticmethod
    def child_ctx(ctx: dict, span: Span) -> dict:
        return {"t": ctx["t"], "s": span.span_id, "b": 1}

    # ------------------------------------------------------------- stores

    def store_for(self, node: str) -> SpanStore:
        with self._lock:
            st = self._stores.get(node)
            if st is None:
                st = self._stores[node] = SpanStore(self.store_limit)
            return st

    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            stores = list(self._stores.values())
        out: List[Span] = []
        for st in stores:
            out.extend(st.snapshot(trace_id))
        out.sort(key=lambda s: s.start)
        return out

    def traces(self) -> List[Dict[str, Any]]:
        """Trace summaries, newest first: root span name, start, total
        duration (max span end - min span start), span count, nodes."""
        by_id: Dict[str, List[Span]] = {}
        for s in self.spans():
            by_id.setdefault(s.trace_id, []).append(s)
        out = []
        for tid, spans in by_id.items():
            start = min(s.start for s in spans)
            end = max(s.start + s.duration for s in spans)
            roots = [s for s in spans if not s.parent_id]
            out.append({
                "trace_id": tid,
                "root": roots[0].name if roots else spans[0].name,
                "start": start,
                "duration": end - start,
                "spans": len(spans),
                "nodes": sorted({s.node for s in spans}),
            })
        out.sort(key=lambda t: t["start"], reverse=True)
        return out

    # ------------------------------------------------------------- notes

    def note_eval(self, eval_id: str, ctx: dict,
                  ts: Optional[float] = None) -> None:
        """Propose-time note: the eval was created under `ctx` at `ts`.
        The FSM's leader hook enqueues the eval inside the apply cone, so
        the queue-wait span is stitched here instead: noted at propose
        time, emitted at dequeue time."""
        with self._lock:
            while len(self._eval_notes) >= self._NOTE_LIMIT:
                self._eval_notes.pop(next(iter(self._eval_notes)))
            self._eval_notes[eval_id] = (ctx, time.time() if ts is None
                                         else ts)

    def take_eval_note(self, eval_id: str) \
            -> Optional[Tuple[dict, float]]:
        with self._lock:
            return self._eval_notes.pop(eval_id, None)


# ===================================================================== export

def chrome_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome-trace (Trace Event Format) JSON for Perfetto / chrome://
    tracing: one complete ("X") event per span, one process row per
    node, timestamps in microseconds."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        node = s.get("node") or "-"
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": node}})
        ev = {"name": s["name"], "ph": "X", "pid": pid, "tid": 0,
              "ts": s["start"] * 1e6, "dur": s["duration"] * 1e6,
              "args": {"trace_id": s["trace_id"],
                       "span_id": s["span_id"],
                       "parent_id": s["parent_id"]}}
        attrs = s.get("attrs")
        if attrs:
            ev["args"].update(attrs)
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ===================================================================== module

# the installed tracer, or None.  Instrumentation sites test this one
# global before doing anything else: the untraced fast path is a module
# attribute load + is-check, nothing more (chaos.py idiom).
active: Optional[Tracer] = None

_tls = threading.local()


def install(tracer: Optional[Tracer]) -> Optional[Tracer]:
    global active
    prev = active
    active = tracer
    return prev


def uninstall() -> Optional[Tracer]:
    return install(None)


def current() -> Optional[dict]:
    """The trace context bound to this thread, or None (unsampled)."""
    return getattr(_tls, "ctx", None)


def bind(ctx: Optional[dict]) -> Optional[dict]:
    """Bind `ctx` as this thread's current trace context; returns the
    previous binding so callers can restore it in a finally block."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


if knobs.get_bool("NOMAD_TPU_TRACE"):
    active = Tracer(sample_rate=knobs.get_float("NOMAD_TPU_TRACE_SAMPLE"))
