"""Reserved RPC-args keys and the forwarding-path propagation contract.

An RPC args dict carries request-scoped context in underscore-prefixed
"reserved" keys alongside the method's own arguments.  Every site that
re-constructs, copies, or filters an args dict on a forwarding path
must preserve (or deliberately consume) every reserved key — PR 18's
drive-found bug was exactly this: the HTTP dispatch rebuilt args and
silently dropped the `_read_mode` shed classification.

This module is that contract, stated once: the key registry, the
declared forwarding sites with the keys each must re-stamp, the strips
that are deliberate consumption, and the wire-header spellings.  The
`context-propagation` static checker
(`nomad_tpu/analysis/context_propagation.py`) reads these declarations
from the AST and fails any forwarding site that drops a reserved key
without an entry here (or an inline `# analysis: allow(...)`).

`restamp()` is the runtime half: the one sanctioned way to rebuild an
args dict at an RPC origin, re-attaching every thread-recoverable key.
"""
from __future__ import annotations

from nomad_tpu import deadline, tracing

# Every reserved key that may ride an RPC args dict.  A key listed here
# and never used is a finding (dead key); an underscore-prefixed key
# used on a forwarding path and NOT listed here is a finding too.
_RESERVED_KEYS = {
    "_trace": "sampled trace context (tracing.TRACE_KEY); hops "
              "re-attach it so one trace spans the forward chain",
    "_deadline": "relative deadline budget (deadline.DEADLINE_KEY), "
                 "re-encoded from the local binding at every hop",
    "_read_mode": "read-path shed classification consumed by the "
                  "brownout gate at dispatch",
    "_forward_hops": "federation hop guard; incremented per forward "
                     "and capped at MAX_FORWARD_HOPS",
}

# Keys recoverable from thread-local state: `restamp()` re-attaches
# these, so an "origin" site that calls it covers all of them.
_THREAD_KEYS = ("_trace", "_deadline")

# qualname -> (kind, keys that site must re-stamp when it builds or
# forwards an args dict).  "origin" sites build fresh args from
# thread-local context (and must cover at least _THREAD_KEYS);
# "forward" sites relay an existing dict and re-encode per-hop keys.
_FORWARDING_SITES = {
    "Endpoints.handle": ("forward", ("_forward_hops", "_deadline")),
    "RegionRouter.route": ("forward", ("_deadline",)),
    "Server.rpc_leader": ("origin", ("_trace", "_deadline")),
    "Server.rpc_region": ("origin", ("_trace", "_deadline")),
    "HTTPServer._rpc": ("origin", ("_trace", "_deadline", "_read_mode")),
    "ApiClient._request": ("forward", ("_deadline",)),
}

# Deliberate consumption: at local dispatch the handler strips every
# reserved key (they are transport context, not method arguments).
# A pop/del of a reserved key at a forwarding site is a finding unless
# the (site, key) pair is listed here or the key is re-stamped later
# in the same function (pop-then-restore, like the hop counter).
_ALLOWED_STRIPS = {
    "Endpoints.handle": ("_trace", "_deadline", "_read_mode",
                         "_forward_hops"),
}

# HTTP spellings of reserved keys: stamping the header is stamping the
# key (the API client re-encodes `_deadline` per retry attempt).
_WIRE_HEADERS = {"X-Nomad-Deadline": "_deadline"}


def restamp(args: dict) -> dict:
    """A copy of `args` with every thread-recoverable reserved key
    re-attached from this thread's context: the sampled trace context
    (when tracing is active and the dict doesn't already carry one) and
    the remaining deadline budget re-encoded for the hop.  Never
    mutates `args`."""
    out = dict(args)
    if tracing.active is not None and tracing.TRACE_KEY not in out:
        ctx = tracing.current()
        if ctx is not None:
            out[tracing.TRACE_KEY] = ctx
    if deadline.DEADLINE_KEY not in out:
        wire = deadline.to_wire()
        if wire is not None:
            out[deadline.DEADLINE_KEY] = wire
    return out
