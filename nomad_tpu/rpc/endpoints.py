"""RPC endpoint registry (reference: the per-struct endpoints registered in
nomad/server.go:264+ — Job/Node/Eval/Alloc/Plan/Deployment/Operator/Status
— with handler names like "Job.Register" nomad/job_endpoint.go:81,
"Eval.Dequeue" eval_endpoint.go:104, "Plan.Submit" plan_endpoint.go:23).

Handlers take an args dict and return plain values; writes on a follower
raise RpcError("not_leader") carrying the leader hint so the caller can
forward (reference: structs.ErrNoLeader / forwardLeader, nomad/rpc.go).
"""
from __future__ import annotations

import time as _time
from typing import Callable, Dict, Optional

from nomad_tpu import deadline, tracing
from nomad_tpu.raft import MessageType, NotLeaderError
from nomad_tpu.structs import Evaluation, EvalStatus
from nomad_tpu.structs.evaluation import EvalTrigger


class RpcError(Exception):
    def __init__(self, kind: str, detail: str = "",
                 leader: Optional[str] = None,
                 retry_after: Optional[float] = None):
        super().__init__(f"{kind}: {detail}")
        self.kind = kind
        self.detail = detail
        self.leader = leader
        # overload refusals (admission_denied/brownout) carry the
        # client's Retry-After hint through the RPC layer to HTTP
        self.retry_after = retry_after


class _DryRunPlanner:
    """Planner that records plans without committing (reference: the
    Job.Plan path runs the scheduler with a no-op planner capturing the
    plan for annotation output)."""

    def __init__(self, store):
        self.store = store
        self.plans = []
        self.evals = []

    def submit_plan(self, plan):
        from nomad_tpu.structs.plan import PlanResult
        self.plans.append(plan)
        return PlanResult(node_update=plan.node_update,
                          node_allocation=plan.node_allocation,
                          node_preemptions=plan.node_preemptions,
                          deployment=plan.deployment,
                          alloc_index=self.store.latest_index)

    def create_evals(self, evals):
        self.evals.extend(evals)

    def update_eval(self, ev):
        pass

    def reblock_eval(self, ev):
        pass

    def refresh_snapshot(self, min_index: int = 0):
        return self.store.snapshot()


class Endpoints:
    def __init__(self, server):
        self.server = server
        self._methods: Dict[str, Callable] = {}
        for name in dir(self):
            if name.startswith("rpc_"):
                method = name[4:].replace("__", ".")
                self._methods[method] = getattr(self, name)

    # ------------------------------------------------------------- dispatch

    def handle(self, method: str, args: dict):
        # cross-region forwarding (reference nomad/rpc.go:21
        # forwardRegion): an explicit region that is not ours routes to
        # that region's servers before any local processing.  The
        # forwarded copy KEEPS the region field — a server whose WAN view
        # is stale may hand the request to the wrong region, and the
        # receiver must be able to forward it on — with a hop counter so
        # two regions with mutually-stale views can't ping-pong forever.
        region = (args or {}).get("region")
        if region and region != self.server.region:
            from nomad_tpu.federation import MAX_FORWARD_HOPS
            fwd = dict(args)
            hops = int(fwd.pop("_forward_hops", 0)) + 1
            if hops > MAX_FORWARD_HOPS:
                raise RpcError(
                    "forward_loop",
                    f"{method} for region {region!r} exceeded "
                    f"{MAX_FORWARD_HOPS} forwarding hops")
            fwd["_forward_hops"] = hops
            # decrement the deadline budget across the hop: decode what
            # the sender gave us, refuse if already spent, and re-encode
            # whatever remains for the next region
            if deadline.DEADLINE_KEY in fwd:
                dprev = deadline.bind(
                    deadline.from_wire(fwd[deadline.DEADLINE_KEY]))
                try:
                    if deadline.check("rpc.forward"):
                        raise RpcError(
                            "deadline_exceeded",
                            f"{method}: budget exhausted before the "
                            f"forward to region {region!r}")
                    fwd[deadline.DEADLINE_KEY] = deadline.to_wire()
                    return self.server.rpc_region(region, method, fwd)
                finally:
                    deadline.bind(dprev)
            return self.server.rpc_region(region, method, fwd)
        fn = self._methods.get(method)
        if fn is None:
            raise RpcError("unknown_method", method)
        # copy before stripping routing fields — the CALLER's dict must
        # come back unchanged (it may retry against another server)
        args = dict(args) if args else {}
        args.pop("region", None)
        args.pop("_forward_hops", None)
        # sampled trace context (absent = unsampled): bind it to this
        # thread for the duration of the dispatch so downstream code —
        # plan enqueue, raft apply — can attach child spans
        tctx = args.pop(tracing.TRACE_KEY, None)
        tracer = tracing.active
        tspan = tprev = None
        if tracer is not None and tctx is not None:
            tspan = tracer.start(tctx, f"rpc.{method}", self.server.name)
            tprev = tracing.bind(tracer.child_ctx(tctx, tspan))
        # per-request consistency on read RPCs (reference QueryOptions
        # riding every RPC): establish the read point before dispatch so
        # the handler's plain store reads serve at it
        mode = args.pop("consistency", None)
        # a read point the HTTP tier already established rides along as
        # `_read_mode`: it classifies the request for brownout shedding
        # (stale sheds last) without triggering a second begin_read
        shed_mode = args.pop("_read_mode", None) or mode
        # request deadline (absent = unbounded): decode the relative
        # wire budget into a local monotonic deadline and bind it for
        # the dispatch so every queueing stage downstream can check it
        dwire = args.pop(deadline.DEADLINE_KEY, None)
        dprev = None
        dbound = dwire is not None
        if dbound:
            dprev = deadline.bind(deadline.from_wire(dwire))
        try:
            if deadline.check("rpc"):
                raise RpcError(
                    "deadline_exceeded",
                    f"{method}: budget exhausted before dispatch")
            # leader brownout: refuse sheddable classes with an honest
            # 503 before any queueing or raft work happens for them
            brownout = getattr(self.server, "brownout", None)
            if brownout is not None:
                retry = brownout.shed(method, shed_mode or "default")
                if retry is not None:
                    raise RpcError(
                        "brownout",
                        f"{method}: leader shedding load",
                        retry_after=retry)
            if mode is not None:
                from nomad_tpu.serving.gate import READ_METHODS
                if method in READ_METHODS:
                    # the read gate is a queueing stage: a bound request
                    # budget caps how long establishing the read point
                    # may retry across vacant leadership (the gate's own
                    # 5s cap otherwise outlives a 1s request many times)
                    rem = deadline.remaining()
                    try:
                        if rem is not None:
                            self.server.serving_gate.begin_read(
                                mode, timeout=min(5.0, max(0.05, rem)))
                        else:
                            self.server.serving_gate.begin_read(mode)
                    except TimeoutError:
                        if deadline.check("read_gate"):
                            raise RpcError(
                                "deadline_exceeded",
                                f"{method}: read point not established "
                                f"inside the request budget")
                        raise
            return fn(args)
        except NotLeaderError as e:
            raise RpcError("not_leader", leader=e.leader)
        finally:
            if dbound:
                deadline.bind(dprev)
            if tspan is not None:
                tracer.finish(tspan)
                tracing.bind(tprev)

    def methods(self):
        return sorted(self._methods)

    # ------------------------------------------------------------- status

    def rpc_Status__Ping(self, args):
        return {"ok": True, "server": self.server.name}

    def rpc_Status__Leader(self, args):
        s = self.server
        if s.raft is None:
            return s.name if s.leader else None
        return s.raft.leader_id

    def rpc_Status__Members(self, args):
        """Serf-style member listing (reference nomad/serf.go members)."""
        s = self.server
        if s.membership is not None:
            return s.membership.member_list()
        peers = [s.name] if s.raft is None else [s.name] + list(s.raft.peers)
        return [{"name": n, "addr": None, "incarnation": 0,
                 "status": "alive"} for n in sorted(set(peers))]

    def rpc_Status__Peers(self, args):
        s = self.server
        if s.raft is None:
            return [s.name]
        return [s.name] + list(s.raft.peers)

    # ------------------------------------------------------------- raft

    def rpc_Raft__Apply(self, args):
        """Leader-side apply for writes forwarded from followers."""
        return self.server.apply_local(args["msg_type"], args["payload"])

    def rpc_Raft__ReadIndex(self, args):
        """Leader half of a follower read (Raft §6.4): confirm leadership
        and return the commit index the follower must apply up to before
        serving locally.  `lease=True` (the default consistency mode)
        serves from a still-valid leader lease with zero quorum rounds;
        `lease=False` (`?consistent`) always pays the heartbeat round."""
        s = self.server
        if s.raft is None:
            return {"index": s.store.latest_index}
        idx = s.raft.read_index(
            timeout=float(args.get("timeout", 5.0)),
            lease_ok=bool(args.get("lease", True)))
        return {"index": idx}

    # ------------------------------------------------------------- jobs

    def rpc_Job__Register(self, args):
        ev = self.server.register_job(args["job"])
        return {"eval_id": ev.id, "job_modify_index":
                args["job"].job_modify_index}

    def rpc_Job__Deregister(self, args):
        ev = self.server.deregister_job(
            args.get("namespace", "default"), args["job_id"],
            purge=args.get("purge", False))
        return {"eval_id": ev.id if ev else None}

    def rpc_Job__GetJob(self, args):
        return self.server.store.job_by_id(
            args.get("namespace", "default"), args["job_id"])

    def rpc_Job__List(self, args):
        ns = args.get("namespace")
        jobs = self.server.store.jobs()
        if ns and ns != "*":        # "*" = all namespaces (wildcard list)
            jobs = [j for j in jobs if j.namespace == ns]
        if args.get("federated"):
            jobs = list(jobs) + self._federated_job_list(ns)
        return jobs

    def _federated_job_list(self, ns):
        """Fan the listing out to every known remote region's leader.
        Dark regions are skipped, not fatal — a federated listing is a
        best-effort union (reference nomad's per-region API: the CLI
        queries regions independently and tolerates missing ones)."""
        from nomad_tpu.raft.transport import Unreachable

        remote = []
        for region in self.server.regions():
            if region == self.server.region:
                continue
            try:
                part = self.server.rpc_region(region, "Job.List", {
                    **({"namespace": ns} if ns else {})})
            except (Unreachable, RpcError):
                continue
            remote.extend(part or [])
        return remote

    def rpc_Job__Plan(self, args):
        """Dry-run scheduling (reference Job.Plan, nomad/job_endpoint.go:
        the scheduler runs against a snapshot with a CapturingPlanner and
        nothing commits; annotations carry the per-group diff)."""
        from nomad_tpu.scheduler import factory as sched_factory
        from nomad_tpu.structs import Evaluation
        import copy as _copy
        job = args["job"]
        server = self.server
        # store.snapshot() may return a shared memoized snapshot — shallow
        # copy before overlaying the hypothetical job so concurrent
        # workers never see the dry-run state
        snap = _copy.copy(server.store.snapshot())
        planner = _DryRunPlanner(server.store)
        snap.jobs = dict(snap.jobs)
        snap.jobs[(job.namespace, job.id)] = job
        ev = Evaluation(
            namespace=job.namespace, priority=job.priority, type=job.type,
            job_id=job.id, triggered_by=EvalTrigger.JOB_REGISTER,
            status=EvalStatus.PENDING, annotate_plan=True)
        sched = sched_factory.new_scheduler(
            job.type if job.type in ("service", "batch", "system",
                                     "sysbatch") else "service",
            snap, planner)
        sched.process(ev)
        plan = planner.plans[-1] if planner.plans else None
        ann = plan.annotations if plan is not None else None
        return {
            "annotations": ann,
            "failed_tg_allocs": getattr(sched, "failed_tg_allocs", None),
            "placements": sum(len(v) for v in
                              plan.node_allocation.values()) if plan else 0,
            "preemptions": sum(len(v) for v in
                               plan.node_preemptions.values()) if plan else 0,
            "job_modify_index": job.job_modify_index,
        }

    def rpc_Job__Dispatch(self, args):
        """Dispatch a parameterized job instance (reference Job.Dispatch):
        materialize a child job carrying the payload/meta."""
        import time as _t
        import uuid as _uuid
        ns = args.get("namespace", "default")
        parent = self.server.store.job_by_id(ns, args["job_id"])
        if parent is None:
            raise RpcError("not_found", args["job_id"])
        if not parent.is_parameterized():
            raise RpcError("bad_request",
                           f"job {args['job_id']} is not parameterized")
        cfg = parent.parameterized
        payload = args.get("payload") or ""
        if cfg.payload == "forbidden" and payload:
            raise RpcError("bad_request", "payload forbidden")
        if cfg.payload == "required" and not payload:
            raise RpcError("bad_request", "payload required")
        meta = dict(args.get("meta") or {})
        missing = [k for k in cfg.meta_required if k not in meta]
        if missing:
            raise RpcError("bad_request", f"missing meta: {missing}")
        unknown = [k for k in meta if k not in cfg.meta_required
                   and k not in cfg.meta_optional]
        if unknown:
            raise RpcError("bad_request", f"unknown meta: {unknown}")
        child = parent.copy()
        child.parent_id = parent.id
        child.id = (f"{parent.id}/dispatch-{int(_t.time())}-"
                    f"{str(_uuid.uuid4())[:8]}")
        child.name = child.id
        child.parameterized = None
        # wire payloads are base64 (matching the reference's []byte JSON
        # encoding); store the decoded bytes
        if isinstance(payload, str):
            import base64 as _b64
            try:
                child.payload = _b64.b64decode(payload, validate=True)
            except Exception:              # noqa: BLE001
                raise RpcError("bad_request", "payload must be base64")
        else:
            child.payload = payload
        child.meta = {**(parent.meta or {}), **meta}
        ev = self.server.register_job(child)
        return {"dispatched_job_id": child.id, "eval_id": ev.id}

    def rpc_Job__Revert(self, args):
        """Revert to a prior version (reference Job.Revert): re-register
        the stored version's job."""
        ns = args.get("namespace", "default")
        prior = self.server.store.job_version(
            ns, args["job_id"], args["version"])
        if prior is None:
            raise RpcError(
                "not_found",
                f"job {args['job_id']} version {args['version']}")
        current = self.server.store.job_by_id(ns, args["job_id"])
        if current is not None and current.version == prior.version:
            raise RpcError("bad_request",
                           "cannot revert to the current version")
        j = prior.copy()
        ev = self.server.register_job(j)
        return {"eval_id": ev.id, "job_version": j.version}

    def rpc_Job__Stability(self, args):
        self.server.set_job_stability(
            args.get("namespace", "default"), args["job_id"],
            args["version"], args["stable"])
        return {}

    def rpc_Job__Summary(self, args):
        return self.server.store.job_summary(
            args.get("namespace", "default"), args["job_id"])

    def rpc_Job__Allocations(self, args):
        return self.server.store.allocs_by_job(
            args.get("namespace", "default"), args["job_id"])

    def rpc_Job__Evaluations(self, args):
        return self.server.store.evals_by_job(
            args.get("namespace", "default"), args["job_id"])

    # ------------------------------------------------------------- nodes

    def rpc_Node__Register(self, args):
        self.server.register_node(args["node"])
        return {"heartbeat_ttl": self.server.config.heartbeat_ttl}

    def rpc_Node__UpdateStatus(self, args):
        """Heartbeats reset the TTL; an explicit status is a real
        transition (init->ready included) that triggers node evals
        (reference Node.UpdateStatus, node_endpoint.go:396)."""
        if args.get("heartbeat") and not args.get("status"):
            ttl = self.server.node_heartbeat(args["node_id"])
            return {"heartbeat_ttl": ttl}
        node = self.server.store.node_by_id(args["node_id"])
        status = args["status"]
        if node is not None and node.status == status:
            # no-op transition; still counts as liveness
            ttl = self.server.node_heartbeat(args["node_id"])
            return {"heartbeat_ttl": ttl, "eval_ids": []}
        evals = self.server.update_node_status(args["node_id"], status)
        ttl = self.server.heartbeats.heartbeat(args["node_id"]) \
            if self.server.leader else self.server.config.heartbeat_ttl
        return {"eval_ids": [e.id for e in evals], "heartbeat_ttl": ttl}

    def rpc_Node__UpdateFingerprint(self, args):
        """Device/attribute re-fingerprint DELTA: coalesces through the
        leader's heartbeat batcher as one NodeFingerprintBatch entry per
        flush instead of a full Node.Register per change.  Returns
        known=False for an unregistered node so the client falls back
        to Node.Register."""
        update = {k: args[k] for k in ("devices", "attributes")
                  if k in args}
        return self.server.node_update_fingerprint(args["node_id"],
                                                   update)

    def rpc_Node__BatchHeartbeat(self, args):
        """Fleet-scale liveness: one RPC re-arms many node TTLs through
        the real heartbeat path (the 10K-agent drivers' steady state —
        the leader coalesces any implied status writes into one
        NodeHeartbeatBatch entry per flush tick)."""
        ttl = self.server.node_heartbeats(args["node_ids"])
        return {"heartbeat_ttl": ttl}

    @staticmethod
    def _redact_node(node):
        """Strip the node secret before it leaves the servers (reference
        node_endpoint.go GetNode clears Node.SecretID)."""
        if node is None or not getattr(node, "secret_id", ""):
            return node
        import copy
        node = copy.copy(node)
        node.secret_id = ""
        return node

    def rpc_Node__List(self, args):
        return [self._redact_node(n) for n in self.server.store.nodes()]

    def rpc_Node__GetNode(self, args):
        return self._redact_node(
            self.server.store.node_by_id(args["node_id"]))

    def rpc_Node__GetAllocs(self, args):
        return self.server.store.allocs_by_node(args["node_id"])

    def rpc_Node__UpdateDrain(self, args):
        self.server.drainer.drain_node(
            args["node_id"], deadline_s=args.get("deadline_s", 3600.0),
            ignore_system_jobs=args.get("ignore_system_jobs", False))
        return {}

    def rpc_Node__CancelDrain(self, args):
        self.server.drainer.cancel_drain(args["node_id"])
        return {}

    def rpc_Node__UpdateEligibility(self, args):
        self.server.apply(MessageType.NODE_UPDATE_ELIGIBILITY,
                          {"node_id": args["node_id"],
                           "eligibility": args["eligibility"]})
        return {}

    def rpc_Node__UpdateAlloc(self, args):
        """Client pushes task/alloc state (reference Node.UpdateAlloc,
        node_endpoint.go:1073: failed allocs trigger reschedule evals)."""
        updates = args["allocs"]
        self.server.apply(MessageType.ALLOC_CLIENT_UPDATE,
                          {"allocs": updates})
        evals = []
        seen_jobs = set()
        for u in updates:
            # terminal allocs lose their secrets leases (vault.go
            # RevokeTokens on alloc stop/GC)
            if u.client_status in ("complete", "failed", "lost"):
                self.server.secrets.revoke_for_alloc(u.id)
            if u.client_status != "failed":
                continue
            stored = self.server.store.alloc_by_id(u.id)
            if stored is None:
                continue
            key = (stored.namespace, stored.job_id)
            if key in seen_jobs:
                continue
            seen_jobs.add(key)
            job = stored.job or self.server.store.job_by_id(*key)
            if job is None or job.stopped():
                continue
            evals.append(Evaluation(
                namespace=stored.namespace, priority=job.priority,
                type=job.type, job_id=job.id,
                triggered_by=EvalTrigger.RETRY_FAILED_ALLOC,
                status=EvalStatus.PENDING))
        if evals:
            self.server.create_evals(evals)
        return {"eval_ids": [e.id for e in evals]}

    def rpc_Node__GetClientAllocs(self, args):
        """Blocking query for a node's allocations (reference
        Node.GetClientAllocs, node_endpoint.go: clients long-poll with
        their last seen index)."""
        store = self.server.store
        min_index = args.get("min_index", 0)
        timeout = min(args.get("timeout", 2.0), 30.0)
        # the long-poll park must not outlive the request budget: a
        # deadline-bound caller gets at most its remaining slice, then
        # the current state (long-poll semantics, not an error)
        rem = deadline.remaining()
        if rem is not None:
            timeout = min(timeout, rem)
        store.wait_for_index(min_index + 1, timeout=timeout)
        return {"index": store.latest_index,
                "allocs": store.allocs_by_node(args["node_id"])}

    def rpc_Node__Deregister(self, args):
        self.server.apply(MessageType.NODE_DEREGISTER,
                          {"node_id": args["node_id"]})
        return {}

    # ------------------------------------------------------------- evals

    def rpc_Eval__GetEval(self, args):
        return self.server.store.eval_by_id(args["eval_id"])

    def rpc_Eval__List(self, args):
        ns = args.get("namespace")
        evals = self.server.store.evals()
        if ns and ns != "*":
            evals = [e for e in evals if e.namespace == ns]
        return evals

    def rpc_Eval__Dequeue(self, args):
        """Worker dequeue with lease token (eval_endpoint.go:104); only the
        leader's broker has evals."""
        gate = getattr(self.server, "admission", None)
        ns = args.get("namespace", "default")
        if gate is not None and gate.enabled:
            # deny-by-503 before touching the broker: an over-limit
            # dequeue flood must not contend the broker lock either
            retry = gate.try_acquire(ns)
            if retry is not None:
                raise RpcError(
                    "admission_denied",
                    f"Eval.Dequeue over limit for namespace {ns!r}",
                    retry_after=retry)
        try:
            ev, token = self.server.broker.dequeue(
                args["schedulers"], timeout=args.get("timeout", 0.1))
        finally:
            if gate is not None and gate.enabled:
                gate.release(ns)
        if ev is None:
            return None
        # wait_index: the leader's store index at dequeue time.  A
        # redelivered eval may already have had a plan committed for it
        # (nack after crash-after-commit, lease expiry, failover); a
        # follower worker scheduling from a snapshot older than this
        # index would not see those allocs and double-place the job
        # (reference eval_endpoint.go Dequeue GetWaitIndex).
        resp = {"eval": ev, "token": token,
                "wait_index": self.server.store.latest_index}
        tracer = tracing.active
        if tracer is not None:
            # hand the eval's sampled trace context (re-noted by the
            # broker at dequeue, after the queue-wait span) to the
            # remote worker so scheduling spans join the trace
            note = tracer.take_eval_note(ev.id)
            if note is not None:
                resp["trace"] = note[0]
        return resp

    def rpc_Eval__Ack(self, args):
        return {"ok": self.server.broker.ack(args["eval_id"], args["token"])}

    def rpc_Eval__Nack(self, args):
        return {"ok": self.server.broker.nack(args["eval_id"], args["token"])}

    def rpc_Eval__Update(self, args):
        self.server.update_eval(args["eval"])
        return {}

    def rpc_Eval__Create(self, args):
        self.server.create_evals(args["evals"])
        return {}

    def rpc_Eval__Reblock(self, args):
        self.server.blocked_evals.block(args["eval"])
        return {}

    # ------------------------------------------------------------- allocs

    def rpc_Alloc__GetAlloc(self, args):
        return self.server.store.alloc_by_id(args["alloc_id"])

    def rpc_Alloc__List(self, args):
        ns = args.get("namespace")
        allocs = self.server.store.allocs()
        if ns and ns != "*":
            allocs = [a for a in allocs if a.namespace == ns]
        return allocs

    def rpc_Alloc__Stop(self, args):
        """Stop a single allocation and reschedule-evaluate its job."""
        a = self.server.store.alloc_by_id(args["alloc_id"])
        if a is None:
            raise RpcError("not_found", args["alloc_id"])
        u = a.copy()
        u.desired_status = "stop"
        u.desired_description = "alloc stopped by user"
        self.server.apply(MessageType.ALLOC_UPDATE, {"allocs": [u]})
        job = a.job or self.server.store.job_by_id(a.namespace, a.job_id)
        ev = Evaluation(
            namespace=a.namespace, priority=job.priority if job else 50,
            type=job.type if job else "service", job_id=a.job_id,
            triggered_by=EvalTrigger.ALLOC_STOP, status=EvalStatus.PENDING)
        self.server.create_evals([ev])
        return {"eval_id": ev.id}

    # ------------------------------------------------------------- plans

    def rpc_Plan__Submit(self, args):
        """Leader-side plan submission (plan_endpoint.go:23): enqueue
        (gated on the submitter's eval lease still being live) and block
        for the applier's result."""
        plan = args["plan"]
        gate = getattr(self.server, "admission", None)
        ns = (plan.job.namespace or "default") if plan.job else "default"
        if gate is not None and gate.enabled:
            # per-namespace bucket keyed on the PLAN's tenant: an
            # abusive tenant's submissions shed here before its load
            # reaches the applier and starves victim tenants
            retry = gate.try_acquire(ns)
            if retry is not None:
                raise RpcError(
                    "admission_denied",
                    f"Plan.Submit over limit for namespace {ns!r}",
                    retry_after=retry)
        try:
            # shed before enqueue: an already-expired submission would
            # only burn an applier slot to produce an unwanted result
            if deadline.check("plan.submit"):
                raise RpcError(
                    "deadline_exceeded",
                    "plan.submit: deadline expired before enqueue")
            pending = self.server.enqueue_plan(plan)
            # clamp the applier wait to the remaining budget so a
            # deadline-bound submitter never parks the full 30 s
            timeout = 30.0
            rem = deadline.remaining()
            if rem is not None:
                timeout = min(timeout, rem)
            return pending.future.result(timeout=timeout)
        finally:
            if gate is not None and gate.enabled:
                gate.release(ns)

    # ------------------------------------------------------------- deploys

    def rpc_Deployment__List(self, args):
        ns = args.get("namespace")
        deps = self.server.store.deployments()
        if ns and ns != "*":
            deps = [d for d in deps if d.namespace == ns]
        return deps

    def rpc_Deployment__GetDeployment(self, args):
        return self.server.store.deployment_by_id(args["deployment_id"])

    def rpc_Deployment__Promote(self, args):
        ok = self.server.deployment_watcher.promote(
            args["deployment_id"], groups=args.get("groups"))
        return {"ok": ok}

    def rpc_Deployment__Fail(self, args):
        return {"ok": self.server.deployment_watcher.fail(
            args["deployment_id"])}

    def rpc_Deployment__Pause(self, args):
        return {"ok": self.server.deployment_watcher.pause(
            args["deployment_id"], args.get("pause", True))}

    def rpc_Deployment__MultiregionFail(self, args):
        """Cross-region failure propagation target: a peer region's
        multiregion deployment failed, fail/revert ours.  Safe on a
        follower — the resulting writes forward to our leader via
        apply()."""
        return {"ok": self.server.deployment_watcher.multiregion_fail(
            args.get("namespace", "default"), args["job_id"],
            args.get("rollout", ""))}

    # ------------------------------------------------------------- operator

    # --- CSI volumes / plugins (reference nomad/csi_endpoint.go)

    def rpc_CSIVolume__List(self, args):
        ns = args.get("namespace")
        return [v.stub() for v in self.server.store.csi_volumes(ns)]

    def rpc_CSIVolume__Get(self, args):
        vol = self.server.store.csi_volume_by_id(
            args.get("namespace", "default"), args["volume_id"])
        if vol is None:
            raise RpcError(f"volume {args['volume_id']} not found")
        return vol

    def rpc_CSIVolume__Register(self, args):
        from nomad_tpu.raft.fsm import MessageType as MT
        self.server.apply(MT.CSI_VOLUME_REGISTER, {"volume": args["volume"]})
        return {}

    def rpc_CSIVolume__Deregister(self, args):
        from nomad_tpu.raft.fsm import MessageType as MT
        self.server.apply(MT.CSI_VOLUME_DEREGISTER, {
            "namespace": args.get("namespace", "default"),
            "volume_id": args["volume_id"],
            "force": args.get("force", False)})
        return {}

    def rpc_CSIVolume__Claim(self, args):
        from nomad_tpu.raft.fsm import MessageType as MT
        self.server.apply(MT.CSI_VOLUME_CLAIM, {
            "namespace": args.get("namespace", "default"),
            "volume_id": args["volume_id"],
            "claim": args["claim"]})
        return {}

    def rpc_CSIPlugin__List(self, args):
        return [p.stub() for p in self.server.store.csi_plugins()]

    def rpc_CSIPlugin__Get(self, args):
        plug = self.server.store.csi_plugin_by_id(args["plugin_id"])
        if plug is None:
            raise RpcError(f"plugin {args['plugin_id']} not found")
        return plug

    def rpc_Operator__SchedulerGetConfiguration(self, args):
        return self.server.store.scheduler_config

    def rpc_Operator__SchedulerSetConfiguration(self, args):
        self.server.apply(MessageType.SCHEDULER_CONFIG,
                          {"config": args["config"]})
        return {}

    def rpc_Operator__RaftGetConfiguration(self, args):
        """The replicated raft membership (reference
        `/v1/operator/raft/configuration`).  Served from the LOCAL node:
        the configuration is replicated state, and an operator debugging
        a split wants each server's own view."""
        s = self.server
        if s.raft is None:
            return {"voters": [s.name], "nonvoters": [], "index": 0,
                    "leader": s.name if s.leader else None, "term": 0}
        return s.raft.configuration()

    def rpc_Operator__RaftRemovePeer(self, args):
        """Force-remove a (possibly dead) server from the raft
        configuration (reference `nomad operator raft remove-peer`)."""
        s = self.server
        if s.raft is None:
            raise RpcError("no_raft", "dev mode has no raft peers")
        try:
            index = s.raft.remove_server(args["name"],
                                         timeout=args.get("timeout", 10.0))
        except NotLeaderError:
            # incl. the transfer-then-demote hop: removing the leader
            # itself transfers leadership first, then the successor
            # performs the removal
            return s.rpc_leader("Operator.RaftRemovePeer", args)
        return {"index": index}

    def rpc_Operator__TransferLeadership(self, args):
        """Graceful leadership handoff (reference `nomad operator
        transfer-leadership`): optional explicit target, else the most
        caught-up voter."""
        s = self.server
        if s.raft is None:
            raise RpcError("no_raft", "dev mode has no raft peers")
        try:
            ok = s.raft.transfer_leadership(args.get("name"))
        except NotLeaderError:
            return s.rpc_leader("Operator.TransferLeadership", args)
        return {"transferred": ok, "leader": s.raft.leader_id}

    def rpc_Operator__Integrity(self, args):
        """Replica-integrity plane view (reference shape:
        `/v1/operator/autopilot/health`): THIS server's last checkpoint
        digest, quarantine state and repair counters — the leader's view
        includes the per-peer report table the majority vote runs over.
        Served locally on purpose: an operator debugging divergence
        wants each replica's own digest, and a quarantined replica must
        still answer."""
        s = self.server
        if s.raft is None:
            return {"server": s.name, "quarantined": False,
                    "quarantine_reason": "", "last": None, "peers": {},
                    "counters": {}, "leader": True}
        view = s.raft.integrity.operator_view()
        view["leader"] = s.raft.is_leader
        return view

    def rpc_Operator__SnapshotSave(self, args):
        if self.server.raft is not None:
            self.server.raft.force_snapshot()
            return {"ok": True}
        path = args.get("path")
        if path:
            self.server.save_snapshot(path)
        return {"ok": True}

    # ------------------------------------------------------------- search

    def rpc_Search__PrefixSearch(self, args):
        """Server-side prefix search across contexts (reference
        nomad/search_endpoint.go:518 PrefixSearch; 20-match truncation
        per context like truncateLimit).  `namespaces`: optional
        visibility filter computed by the agent from the caller's ACL."""
        prefix = args.get("prefix", "")
        context = args.get("context", "all")
        visible = args.get("namespaces")   # None = all namespaces
        store = self.server.store

        def ns_ok(ns):
            return visible is None or ns in visible

        out, trunc = {}, {}

        def add(name, ids):
            matches = sorted(i for i in ids if i.startswith(prefix))
            trunc[name] = len(matches) > 20
            out[name] = matches[:20]

        if context in ("all", "jobs"):
            add("jobs", [j.id for j in store.jobs() if ns_ok(j.namespace)])
        if context in ("all", "nodes"):
            add("nodes", [n.id for n in store.nodes()])
        if context in ("all", "evals"):
            add("evals", [e.id for e in store.evals()
                          if ns_ok(e.namespace)])
        if context in ("all", "allocs"):
            add("allocs", [a.id for a in store.allocs()
                           if ns_ok(a.namespace)])
        if context in ("all", "deployment"):
            add("deployment", [d.id for d in store.deployments()
                               if ns_ok(d.namespace)])
        if context in ("all", "plugins"):
            add("plugins", [p.get("id", "") if isinstance(p, dict) else p.id
                            for p in store.csi_plugins()])
        if context in ("all", "volumes"):
            add("volumes", [v.id for v in store.csi_volumes()
                            if ns_ok(v.namespace)])
        if context in ("all", "namespaces"):
            add("namespaces", [ns.name for ns in store.namespaces()])
        return {"matches": out, "truncations": trunc}

    # ------------------------------------------------------------- namespaces

    def rpc_Namespace__List(self, args):
        return self.server.namespaces()

    def rpc_Namespace__Upsert(self, args):
        try:
            self.server.upsert_namespace(
                args["name"], args.get("description", ""),
                args.get("quota", ""))
        except ValueError as e:
            raise RpcError("bad_request", str(e))
        return {}

    def rpc_Namespace__Delete(self, args):
        try:
            self.server.delete_namespace(args["name"])
        except ValueError as e:
            raise RpcError("bad_request", str(e))
        return {}

    # ------------------------------------------------------------- quotas

    def rpc_Quota__List(self, args):
        return self.server.quota_specs()

    def rpc_Quota__GetQuota(self, args):
        spec = self.server.quota_spec(args["name"])
        if spec is None:
            raise RpcError("not_found", args["name"])
        return spec

    def rpc_Quota__Upsert(self, args):
        self.server.upsert_quota_spec(args["spec"])
        return {}

    def rpc_Quota__Delete(self, args):
        try:
            self.server.delete_quota_spec(args["name"])
        except ValueError as e:
            raise RpcError("bad_request", str(e))
        return {}

    def rpc_Quota__Usage(self, args):
        ns = args.get("namespace")
        if ns and ns != "*":
            return {ns: self.server.quota_usage(ns)}
        return self.server.quota_usages()

    # ------------------------------------------------------------- scaling

    def rpc_Job__Scale(self, args):
        try:
            ev = self.server.scale_job(
                args.get("namespace", "default"), args["job_id"],
                args["group"], count=args.get("count"),
                message=args.get("message", ""),
                error=bool(args.get("error", False)),
                meta=args.get("meta"))
        except ValueError as e:
            raise RpcError("bad_request", str(e))
        return {"eval_id": ev.id if ev is not None else None}

    def rpc_Job__ScaleStatus(self, args):
        st = self.server.job_scale_status(
            args.get("namespace", "default"), args["job_id"])
        if st is None:
            raise RpcError("not_found", args["job_id"])
        return st

    def rpc_Scaling__ListPolicies(self, args):
        """reference nomad/scaling_endpoint.go ListPolicies: one row per
        (job, group) scaling stanza."""
        out = []
        for job, group, pol in self.server.store.scaling_policies(
                args.get("namespace")):
            out.append({
                "id": f"{job.namespace}/{job.id}/{group}",
                "namespace": job.namespace,
                "target": {"Namespace": job.namespace, "Job": job.id,
                           "Group": group},
                "min": pol.min, "max": pol.max, "enabled": pol.enabled,
            })
        return out

    def rpc_Scaling__GetPolicy(self, args):
        pid = args["id"]
        for job, group, pol in self.server.store.scaling_policies(None):
            if f"{job.namespace}/{job.id}/{group}" == pid:
                return {"id": pid, "namespace": job.namespace,
                        "target": {"Namespace": job.namespace,
                                   "Job": job.id, "Group": group},
                        "min": pol.min, "max": pol.max,
                        "enabled": pol.enabled, "policy": pol.policy}
        raise RpcError("not_found", pid)

    # ------------------------------------------------------------- services

    def rpc_Service__Upsert(self, args):
        self.server.apply(MessageType.SERVICE_REGISTER,
                          {"services": args["services"]})
        return {}

    def rpc_Service__DeleteByAlloc(self, args):
        self.server.apply(MessageType.SERVICE_DEREGISTER,
                          {"alloc_id": args["alloc_id"]})
        return {}

    def rpc_Service__Delete(self, args):
        self.server.apply(MessageType.SERVICE_DEREGISTER,
                          {"ids": [args["id"]]})
        return {}

    def rpc_Service__List(self, args):
        """Grouped {service_name: count} listing (reference
        nomad/service_registration_endpoint.go List)."""
        svcs = self.server.store.services(args.get("namespace"))
        names = {}
        for s in svcs:
            names.setdefault((s.namespace, s.service_name), 0)
            names[(s.namespace, s.service_name)] += 1
        return [{"namespace": ns, "service_name": n, "instances": c}
                for (ns, n), c in sorted(names.items())]

    def rpc_Service__GetService(self, args):
        return self.server.store.services_by_name(
            args.get("namespace", "default"), args["service_name"])

    # ------------------------------------------------------------- secrets

    def _require_leader(self):
        s = self.server
        if s.raft is not None and not s.leader:
            raise NotLeaderError(s.raft.leader_id)

    def rpc_Secrets__Put(self, args):
        """Admin write into the embedded KV (the stand-in for seeding
        Vault; reference operators do this against Vault directly).
        With ACLs on, only a management token may seed secrets."""
        self._require_leader()
        if self.server.acl_enabled:
            acl = self.server.resolve_token(args.get("token", ""))
            if acl is None or not acl.management:
                raise RpcError("permission_denied",
                               "Secrets.Put requires a management token")
        return {"version": self.server.secrets.put(
            args["path"], dict(args.get("data") or {}))}

    def rpc_Secrets__Derive(self, args):
        """Per-task token derivation (reference nomad/vault.go
        CreateToken via client_endpoint DeriveVaultToken): policies come
        from the task's vault stanza in the server's own state, never
        from the caller.  The caller must prove it IS the node the alloc
        runs on — node id + node secret (node_endpoint.go
        deriveVaultToken NodeSecretID check) — so a compromised alloc
        cannot mint tokens for tasks on other machines."""
        self._require_leader()
        import hmac
        node = self.server.store.node_by_id(args.get("node_id", ""))
        secret = args.get("node_secret_id", "")
        if (node is None or not node.secret_id or not secret
                or not hmac.compare_digest(node.secret_id, secret)):
            raise RpcError("permission_denied", "node secret mismatch")
        alloc = self.server.store.alloc_by_id(args["alloc_id"])
        if alloc is None or alloc.job is None:
            raise RpcError("not_found", "alloc or its job")
        if alloc.node_id != node.id:
            raise RpcError("permission_denied",
                           "alloc does not run on the requesting node")
        if alloc.terminal_status() or alloc.client_terminal_status():
            # revocation on stop must not be bypassed by a re-derive
            raise RpcError("invalid", "alloc is terminal")
        tg = alloc.job.lookup_task_group(alloc.task_group)
        task = next((t for t in (tg.tasks if tg else [])
                     if t.name == args["task"]), None)
        if task is None or not task.vault:
            raise RpcError("invalid", "task has no vault stanza")
        policies = list(task.vault.get("policies") or [])
        ttl = task.vault.get("ttl_s")
        return self.server.secrets.derive_token(
            alloc.id, task.name, policies,
            float(ttl) if ttl else None)

    def rpc_Secrets__Renew(self, args):
        self._require_leader()
        try:
            return self.server.secrets.renew(args["token"])
        except Exception as e:                       # noqa: BLE001
            raise RpcError("invalid", str(e))

    def rpc_Secrets__Read(self, args):
        self._require_leader()
        try:
            data, version = self.server.secrets.read(
                args["path"], args.get("token", ""))
        except Exception as e:                       # noqa: BLE001
            raise RpcError("invalid", str(e))
        return {"data": data, "version": version}

    def rpc_Secrets__Version(self, args):
        self._require_leader()
        try:
            return {"version": self.server.secrets.version(
                args["path"], args.get("token", ""))}
        except Exception as e:                       # noqa: BLE001
            raise RpcError("invalid", str(e))

    # ------------------------------------------------------------- regions

    def rpc_Status__Regions(self, args):
        return self.server.regions()
