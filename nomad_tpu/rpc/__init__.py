"""RPC layer (reference: nomad/rpc.go — msgpack-RPC over yamux TCP with
leader/region forwarding, plus the connection pool in helper/pool).

The TPU build's host RPC is a framed-pickle protocol with the same shape:
a method-dispatch endpoint registry (`Endpoints`), leader forwarding for
writes issued on followers, an in-process channel riding the Raft
InMemTransport for multi-server tests, and a real TCP server/client pair
for out-of-process agents.
"""
from nomad_tpu.rpc.endpoints import Endpoints, RpcError
from nomad_tpu.rpc.tcp import TcpRpcClient, TcpRpcServer

__all__ = ["Endpoints", "RpcError", "TcpRpcServer", "TcpRpcClient"]
