"""TCP RPC transport (reference: nomad/rpc.go msgpack-RPC over yamux TCP +
the connection pool in helper/pool; TLS wrap analog = HMAC frame auth).

Framing: 4-byte big-endian length + 32-byte HMAC-SHA256 tag + pickled
{"method", "args"} request; same framing for the {"result"} |
{"error", "kind", "leader"} response.  Because payloads are pickled, a
frame is only unpickled after its HMAC verifies — so a server is only
reachable by peers holding the cluster secret.  Binding beyond loopback
without a secret is refused.
"""
from __future__ import annotations

import hashlib
import hmac
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

from nomad_tpu import chaos
from nomad_tpu.rpc.endpoints import Endpoints, RpcError

_HDR = struct.Struct(">I")
_TAG_LEN = 32
MAX_FRAME = 256 * 1024 * 1024
_NO_SECRET = b"nomad-tpu-loopback"


def _tag(secret: bytes, blob: bytes) -> bytes:
    return hmac.new(secret, blob, hashlib.sha256).digest()


def _send_frame(sock: socket.socket, obj, secret: bytes) -> None:
    blob = pickle.dumps(obj)
    sock.sendall(_HDR.pack(len(blob)) + _tag(secret, blob) + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket, secret: bytes):
    (length,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if length > MAX_FRAME:
        raise ConnectionError(f"frame too large: {length}")
    tag = _recv_exact(sock, _TAG_LEN)
    blob = _recv_exact(sock, length)
    # authenticate BEFORE unpickling: pickle.loads on attacker bytes is
    # arbitrary code execution
    if not hmac.compare_digest(tag, _tag(secret, blob)):
        raise ConnectionError("bad frame auth")
    return pickle.loads(blob)


# methods safe to transparently resend after a connection error (reads);
# writes must not be re-executed — the server may have applied them before
# the connection dropped
def _is_idempotent(method: str) -> bool:
    if method.startswith("Status."):
        return True
    verb = method.split(".", 1)[-1]
    return (verb.startswith("Get") or verb.startswith("List")
            or verb in ("Allocations", "Evaluations", "Peers",
                        "SchedulerGetConfiguration"))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        endpoints: Endpoints = self.server.endpoints      # type: ignore
        secret: bytes = self.server.secret                # type: ignore
        sock = self.request
        while True:
            try:
                req = _recv_frame(sock, secret)
            except (ConnectionError, EOFError, OSError):
                return
            try:
                # deadline propagation: the client ships its remaining
                # budget (seconds); refuse work that is already stale
                # rather than burn server time on an abandoned request
                if req.get("deadline", 1.0) <= 0:
                    raise RpcError("timeout", "deadline exceeded")
                result = endpoints.handle(req["method"], req.get("args"))
                resp = {"result": result}
            except RpcError as e:
                resp = {"error": e.detail or e.kind, "kind": e.kind,
                        "leader": e.leader}
            except Exception as e:                         # noqa: BLE001
                resp = {"error": str(e), "kind": "internal"}
            try:
                _send_frame(sock, resp, secret)
            except OSError:
                return


class TcpRpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, endpoints: Endpoints, host: str = "127.0.0.1",
                 port: int = 0, secret: Optional[bytes] = None):
        if secret is None and host not in ("127.0.0.1", "localhost", "::1"):
            raise ValueError(
                "refusing to serve pickled RPC beyond loopback without a "
                "cluster secret (pass secret=...)")
        super().__init__((host, port), _Handler)
        self.endpoints = endpoints
        self.secret = secret or _NO_SECRET
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self.server_address

    def start(self) -> None:
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="rpc-tcp", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


class TcpRpcClient:
    """Pooled client: one persistent connection per address, redial on
    error, leader-redirect follow via an address book (helper/pool +
    forwardLeader in the reference)."""

    # wait-graph (nomad_tpu.analysis)
    _LOCK_BLOCKING_OK = {
        "_lock": "serializes one request/response round trip on the "
                 "pooled socket; interleaved frames would corrupt the "
                 "stream (socket timeout bounds the stall)",
    }

    def __init__(self, address, addr_book: Optional[Dict[str, tuple]] = None,
                 timeout: float = 35.0, secret: Optional[bytes] = None):
        self.address = tuple(address)
        self.addr_book = addr_book or {}
        self.timeout = timeout
        self.secret = secret or _NO_SECRET
        self._lock = threading.Lock()
        self._socks: Dict[tuple, socket.socket] = {}

    def _sock(self, addr) -> socket.socket:
        s = self._socks.get(addr)
        if s is None:
            s = socket.create_connection(addr, timeout=self.timeout)
            s.settimeout(self.timeout)
            self._socks[addr] = s
        return s

    def _roundtrip(self, addr, method: str, args: dict,
                   deadline: Optional[float] = None):
        if chaos.active is not None:
            chaos.maybe_delay()
            if chaos.active.should("rpc.drop"):
                with self._lock:
                    s = self._socks.pop(addr, None)
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise ConnectionError("chaos: rpc.drop")
        frame = {"method": method, "args": args}
        with self._lock:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RpcError("timeout",
                                   f"deadline exceeded calling {method}")
                frame["deadline"] = remaining
            per_call = self.timeout if remaining is None \
                else min(self.timeout, remaining)
            try:
                sock = self._sock(addr)
                sock.settimeout(per_call)
                _send_frame(sock, frame, self.secret)
                return _recv_frame(sock, self.secret)
            except (ConnectionError, OSError):
                # redial; resend only reads — a write may already have been
                # applied server-side before the connection dropped
                self._socks.pop(addr, None)
                if not _is_idempotent(method):
                    raise
                sock = self._sock(addr)
                sock.settimeout(per_call)
                _send_frame(sock, frame, self.secret)
                return _recv_frame(sock, self.secret)

    @staticmethod
    def _backoff(delay: float, deadline: Optional[float]) -> float:
        """Sleep `delay` with jitter (bounded by the deadline); return the
        next delay of the exponential schedule."""
        jittered = delay * (0.5 + random.random() * 0.5)
        if deadline is not None:
            jittered = min(jittered, max(0.0, deadline - time.monotonic()))
        if jittered > 0:
            time.sleep(jittered)
        return min(delay * 2.0, 1.0)

    def call(self, method: str, args: Optional[dict] = None,
             retries: int = 2, deadline: Optional[float] = None,
             _redirects: int = 2):
        """Issue one RPC with exponential-backoff retry.

        `deadline` is a seconds budget for the WHOLE call (all attempts,
        backoff included); the remaining budget ships in the frame so the
        server can drop work the client has already given up on.
        Connection errors are retried only for idempotent methods; a
        `not_leader` rejection was never executed, so leader-forwarding
        retries any method."""
        args = args or {}
        dl = None if deadline is None else time.monotonic() + deadline
        addr = self.address
        delay = 0.05
        attempts_left = max(0, retries)
        redirects_left = max(0, _redirects)
        while True:
            try:
                resp = self._roundtrip(addr, method, args, dl)
            except (ConnectionError, OSError):
                expired = dl is not None and time.monotonic() >= dl
                if not _is_idempotent(method) or attempts_left <= 0 \
                        or expired:
                    raise
                attempts_left -= 1
                delay = self._backoff(delay, dl)
                continue
            if "error" not in resp:
                return resp["result"]
            if resp.get("kind") == "not_leader" and redirects_left > 0:
                redirects_left -= 1
                leader_addr = self.addr_book.get(resp.get("leader"))
                if leader_addr is not None:
                    addr = tuple(leader_addr)
                    continue
                # no leader hint (election in progress): back off and
                # re-ask the same server, which will know the new leader
                if dl is None or time.monotonic() < dl:
                    delay = self._backoff(delay, dl)
                    continue
            raise RpcError(resp.get("kind", "internal"),
                           resp.get("error", ""), resp.get("leader"))

    def close(self) -> None:
        with self._lock:
            for s in self._socks.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._socks.clear()
