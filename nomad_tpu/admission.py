"""Admission control + leader brownout for the control plane.

Two cooperating overload valves, both deny-by-refusal (an explicit 503
with ``Retry-After``), never accept-then-drop:

- :class:`AdmissionGate` — a per-namespace token bucket plus a bounded
  per-namespace concurrency gate, consulted at HTTP ingress and at the
  ``Eval.Dequeue``/``Plan.Submit`` RPC edges.  Buckets are keyed on the
  PR 13 namespace plumbing, so one abusive tenant exhausts *its own*
  bucket and sheds before any victim tenant does.  Disabled by default
  (both knobs zero): the steady-state cost is one attribute load.

- :class:`BrownoutMonitor` — leader-side graceful degradation driven by
  the raft proposal-queue depth and commit→apply lag.  Load is shed in
  strict order: new job submissions first, then linearizable reads,
  stale-consistency reads last — and NEVER the heartbeat / replication
  / lease plumbing, so a scheduler storm cannot depose a healthy leader
  by starving its liveness path.

Knobs (all env):
    NOMAD_TPU_ADMIT_RATE         tokens/sec refilled per namespace
    NOMAD_TPU_ADMIT_BURST        bucket capacity (default 2x rate)
    NOMAD_TPU_ADMIT_CONCURRENCY  in-flight requests per namespace
    NOMAD_TPU_BROWNOUT_DEPTH     proposal-queue depth at brownout edge
    NOMAD_TPU_BROWNOUT_LAG       commit->apply lag (entries) at the edge
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from nomad_tpu import knobs
from nomad_tpu.telemetry import global_metrics
from nomad_tpu.utils import requires_lock

# one abusive tenant cannot also blow up the bucket table itself: the
# namespace cardinality the gate tracks is bounded, oldest-idle evicted
_MAX_TRACKED_NAMESPACES = 1024


class AdmissionDenied(Exception):
    """Request refused at admission; retry_after is the client hint."""

    def __init__(self, detail: str, retry_after: float = 1.0):
        super().__init__(detail)
        self.retry_after = retry_after


class AdmissionGate:
    # Lock discipline (see nomad_tpu.analysis): the bucket and inflight
    # tables are only touched under `self._lock`.
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_buckets", "_inflight"})

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_concurrency: Optional[int] = None):
        self.rate = knobs.get_float("NOMAD_TPU_ADMIT_RATE") \
            if rate is None else float(rate)
        self.burst = knobs.get_float("NOMAD_TPU_ADMIT_BURST") \
            if burst is None else float(burst)
        if self.burst <= 0.0:
            self.burst = max(1.0, 2.0 * self.rate)
        self.max_concurrency = knobs.get_int(
            "NOMAD_TPU_ADMIT_CONCURRENCY") \
            if max_concurrency is None else int(max_concurrency)
        self.enabled = self.rate > 0.0 or self.max_concurrency > 0
        self._lock = threading.Lock()
        # namespace -> [tokens, last_refill_monotonic]
        self._buckets: Dict[str, list] = {}
        self._inflight: Dict[str, int] = {}

    # ------------------------------------------------------------ gate

    def try_acquire(self, namespace: str, cost: float = 1.0) \
            -> Optional[float]:
        """Admit one request for `namespace`: returns None when admitted
        (caller owes a release() when the concurrency gate is on), else
        the suggested Retry-After in seconds.  Admission is all-or-
        nothing — a denial consumes neither tokens nor a slot."""
        if not self.enabled:
            return None
        ns = namespace or "default"
        with self._lock:
            if self.max_concurrency > 0 and \
                    self._inflight.get(ns, 0) >= self.max_concurrency:
                global_metrics.incr(f"admission.denied.concurrency.{ns}")
                return self._retry_after_locked(ns, cost)
            if self.rate > 0.0:
                bucket = self._bucket_locked(ns)
                if bucket[0] < cost:
                    global_metrics.incr(f"admission.denied.rate.{ns}")
                    return self._retry_after_locked(ns, cost)
                bucket[0] -= cost
            if self.max_concurrency > 0:
                self._inflight[ns] = self._inflight.get(ns, 0) + 1
            global_metrics.incr(f"admission.admitted.{ns}")
            return None

    def release(self, namespace: str) -> None:
        if not self.enabled or self.max_concurrency <= 0:
            return
        ns = namespace or "default"
        with self._lock:
            n = self._inflight.get(ns, 0)
            if n <= 1:
                self._inflight.pop(ns, None)
            else:
                self._inflight[ns] = n - 1

    def admit(self, namespace: str, cost: float = 1.0) -> None:
        """try_acquire that raises AdmissionDenied instead of returning
        a hint (the RPC-edge form; callers still owe release())."""
        retry = self.try_acquire(namespace, cost)
        if retry is not None:
            raise AdmissionDenied(
                f"namespace {namespace or 'default'!r} over admission "
                f"limit", retry_after=retry)

    # ---------------------------------------------------------- innards

    @requires_lock("_lock")
    def _bucket_locked(self, ns: str) -> list:
        now = time.monotonic()
        bucket = self._buckets.get(ns)
        if bucket is None:
            if len(self._buckets) >= _MAX_TRACKED_NAMESPACES:
                # evict the stalest bucket: an idle one is full anyway
                stalest = min(self._buckets, key=lambda k:
                              self._buckets[k][1])
                del self._buckets[stalest]
            bucket = self._buckets[ns] = [self.burst, now]
        else:
            bucket[0] = min(self.burst,
                            bucket[0] + (now - bucket[1]) * self.rate)
            bucket[1] = now
        return bucket

    @requires_lock("_lock")
    def _retry_after_locked(self, ns: str, cost: float) -> float:
        if self.rate <= 0.0:
            return 1.0                  # concurrency-only: pure backoff
        bucket = self._buckets.get(ns)
        tokens = bucket[0] if bucket is not None else self.burst
        return max(0.05, (cost - tokens) / self.rate)


# shed ordering (brownout level at which each class is refused):
#   level >= 1: new job submissions — fresh work is the cheapest to
#               refuse; the client retries after the storm
#   level >= 2: linearizable reads — they cost leader rounds
#   level >= 3: stale reads — last, they cost only local store time
# NEVER shed: heartbeat/liveness, raft replication plumbing, and the
# lease-settlement RPCs (ack/nack) — refusing those turns an overload
# into an availability incident (expired fleets, deposed leaders,
# stranded leases).
SHED_SUBMIT = frozenset({
    "Job.Register", "Job.Deregister", "Job.Dispatch", "Job.Scale",
    "Job.Revert", "Job.Plan",
})
SHED_NEVER = frozenset({
    "Node.UpdateStatus", "Node.BatchHeartbeat", "Node.Register",
    "Node.Deregister", "Node.UpdateAlloc",
    "Raft.Apply", "Raft.ReadIndex",
    "Eval.Ack", "Eval.Nack", "Eval.Dequeue", "Eval.Update",
    "Eval.Create", "Eval.Reblock", "Plan.Submit",
    "Status.Ping", "Status.Leader", "Status.Members", "Status.Peers",
})


class BrownoutMonitor:
    """Leader overload classifier.  level() samples the raft signals at
    most every `interval` seconds (a stale-by-50ms level is fine; the
    per-request cost must stay one monotonic read + compare)."""

    def __init__(self, server, interval: float = 0.05):
        self.server = server
        self.interval = interval
        self.depth_hi = knobs.get_int("NOMAD_TPU_BROWNOUT_DEPTH")
        self.lag_hi = knobs.get_int("NOMAD_TPU_BROWNOUT_LAG")
        self._level = 0
        self._sampled_at = 0.0
        self._sample_lock = threading.Lock()

    def level(self) -> int:
        now = time.monotonic()
        if now - self._sampled_at < self.interval:
            return self._level
        # non-blocking: concurrent requests ride the stale sample
        # instead of convoying on the sampler
        if not self._sample_lock.acquire(blocking=False):
            return self._level
        try:
            self._sampled_at = now
            self._level = self._compute()
            global_metrics.set_gauge("brownout.level", float(self._level))
            return self._level
        finally:
            self._sample_lock.release()

    def _compute(self) -> int:
        raft = self.server.raft
        if raft is not None:
            depth = raft.proposal_depth()
            lag = max(0, raft.commit_index - raft.last_applied)
        else:
            depth = self.server.plan_queue.depth()
            lag = 0
        severity = max(depth / max(1, self.depth_hi),
                       lag / max(1, self.lag_hi))
        if severity < 1.0:
            return 0
        if severity < 2.0:
            return 1
        if severity < 4.0:
            return 2
        return 3

    def shed(self, method: str, consistency: str = "default") \
            -> Optional[float]:
        """Retry-After seconds if `method` must be refused at the
        current brownout level, else None.  The shed decision is made
        BEFORE any queueing or raft work happens for the request."""
        if method in SHED_NEVER:
            return None
        lvl = self.level()
        if lvl <= 0:
            return None
        from nomad_tpu.serving.gate import READ_METHODS, STALE
        if method in SHED_SUBMIT:
            pass                          # shed first, from level 1
        elif method in READ_METHODS:
            if consistency == STALE:
                if lvl < 3:
                    return None           # stale reads shed last
            elif lvl < 2:
                return None
        else:
            # unclassified mutations ride with submissions but only
            # from level 2 (deeper overload)
            if lvl < 2:
                return None
        global_metrics.incr(f"brownout.shed.{method}")
        return max(0.1, self.interval * 4 * lvl)
